"""``Tuner`` / ``tune.run`` driver APIs.

Parity with ``python/ray/tune/tuner.py`` and ``tune/tune.py``: expand the
param space into trials, drive them through the ``TrialRunner``, return a
``ResultGrid`` / ``ExperimentAnalysis``.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import RunConfig
from ray_tpu.tune.analysis import ExperimentAnalysis, ResultGrid
from ray_tpu.tune.execution import TrialRunner
from ray_tpu.tune.logger import (Callback, CSVLoggerCallback,
                                 JsonLoggerCallback)
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import Trial


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: Optional[int] = None  # None = searcher's own budget, else 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[Any] = None
    search_alg: Optional[Searcher] = None
    time_budget_s: Optional[float] = None
    seed: Optional[int] = None


def run(trainable,
        config: Optional[Dict[str, Any]] = None,
        *,
        num_samples: Optional[int] = None,
        metric: Optional[str] = None,
        mode: str = "max",
        stop: Optional[Any] = None,
        scheduler=None,
        search_alg: Optional[Searcher] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_concurrent_trials: Optional[int] = None,
        max_failures: int = 0,
        checkpoint_freq: int = 0,
        checkpoint_at_end: bool = False,
        callbacks: Optional[List[Callback]] = None,
        local_dir: Optional[str] = None,
        name: Optional[str] = None,
        time_budget_s: Optional[float] = None,
        verbose: int = 1,
        resume_from: Optional[str] = None,
        sync_config=None,
        seed: Optional[int] = None) -> ExperimentAnalysis:
    """Run an experiment (reference ``tune/tune.py:run``)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    name = name or f"{_trainable_name(trainable)}_{time.strftime('%Y%m%d_%H%M%S')}"
    searcher = None
    if resume_from:
        trials = TrialRunner.load_experiment_state(resume_from)
    elif search_alg is not None:
        # live searcher supplies configs during the run
        if hasattr(search_alg, "set_space") and (
                config or num_samples is not None):
            # an explicit run() config/num_samples overrides the
            # constructor-supplied space/budget; None leaves each in place
            search_alg.set_space(config or None, num_samples)
        trials = []
        searcher = search_alg
    else:
        gen = BasicVariantGenerator(config or {}, num_samples or 1, seed=seed)
        trials = []
        while True:
            cfg = gen.suggest(f"trial_{len(trials)}")
            if cfg is None:
                break
            trials.append(Trial(cfg, trial_id=f"trial_{len(trials)}"))
    callbacks = list(callbacks or [])
    if verbose:
        callbacks += [JsonLoggerCallback(), CSVLoggerCallback()]
    runner = TrialRunner(
        trainable, trials, scheduler=scheduler, stop=stop, metric=metric,
        mode=mode, max_concurrent_trials=max_concurrent_trials,
        max_failures=max_failures, checkpoint_freq=checkpoint_freq,
        checkpoint_at_end=checkpoint_at_end,
        resources_per_trial=resources_per_trial, callbacks=callbacks,
        local_dir=local_dir, experiment_name=name, searcher=searcher,
        time_budget_s=time_budget_s, sync_config=sync_config)
    finished = runner.run()
    return ExperimentAnalysis(finished, metric=metric, mode=mode)


def _trainable_name(trainable) -> str:
    return getattr(trainable, "__name__", "trainable")


class Tuner:
    """Reference ``tune/tuner.py:Tuner``."""

    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_path: Optional[str] = None

    @classmethod
    def restore(cls, path: str, trainable) -> "Tuner":
        t = cls(trainable)
        t._restore_path = path
        return t

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        analysis = run(
            self._trainable,
            config=self.param_space,
            num_samples=tc.num_samples,
            metric=tc.metric,
            mode=tc.mode,
            scheduler=tc.scheduler,
            search_alg=tc.search_alg,
            max_concurrent_trials=tc.max_concurrent_trials,
            max_failures=self.run_config.failure_config.max_failures,
            checkpoint_freq=(
                self.run_config.checkpoint_config.checkpoint_frequency),
            local_dir=self.run_config.storage_path,
            name=self.run_config.name,
            time_budget_s=tc.time_budget_s,
            resume_from=self._restore_path,
            sync_config=getattr(self.run_config, "sync_config", None),
            seed=tc.seed,
        )
        return ResultGrid(analysis)
