"""Trial schedulers: FIFO, ASHA/HyperBand, median stopping, PBT.

Parity with ``python/ray/tune/schedulers/``:
- ``FIFOScheduler`` (fifo.py)
- ``AsyncHyperBandScheduler`` / ASHA (async_hyperband.py) — rung-based early
  stopping with reduction factor and brackets.
- ``HyperBandScheduler`` (hyperband.py) — synchronous banded variant; here
  implemented on the same rung machinery with band-synchronised cutoffs.
- ``MedianStoppingRule`` (median_stopping_rule.py)
- ``PopulationBasedTraining`` (pbt.py) — exploit (clone top performer's
  checkpoint) + explore (perturb hyperparams) at a fixed interval.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import Domain
from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    """``mode=None`` means "not configured": ``set_search_properties``
    fills it from ``run()``'s mode. A constructor-supplied ``mode='min'``
    must survive run()'s 'max' default (scores are negated for min)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        # set by the TrialRunner so schedulers that act on trials other
        # than the one currently reporting (PBT exploit, HyperBand band
        # cuts) can reach the executor
        self._runner = None

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        if self.metric is None:
            self.metric = metric
        if self.mode is None and mode:
            self.mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        if self.metric is None or self.metric not in result:
            return None
        v = float(result[self.metric])
        return -v if self.mode == "min" else v

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]):
        pass

    def on_trial_error(self, trial: Trial):
        pass

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        return None

    def may_resume(self, trial: Trial) -> bool:
        """Whether a PAUSED trial is eligible to restart now. Synchronous
        schedulers return False while the trial awaits a band cut."""
        return True

    def release_holds(self):
        """Called by the runner when no trial is runnable and every paused
        trial is held: resolve whatever synchronization is pending so the
        experiment can make progress."""
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Rung:
    def __init__(self, milestone: float):
        self.milestone = milestone
        self.scores: List[float] = []

    def cutoff(self, rf: float) -> Optional[float]:
        if not self.scores:
            return None
        s = sorted(self.scores)
        # top 1/rf survive: cutoff at the (1 - 1/rf) quantile
        k = int(len(s) * (1 - 1.0 / rf))
        k = min(max(k, 0), len(s) - 1)
        return s[k]


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference ``async_hyperband.py``): per-bracket rungs at
    ``grace_period * rf^k``; a trial reaching a rung is stopped if its score
    is below the rung's top-1/rf cutoff."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 max_t: float = 100, grace_period: float = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        super().__init__(metric, mode, time_attr)
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self._brackets: List[List[_Rung]] = []
        for b in range(brackets):
            rungs = []
            t = grace_period * (reduction_factor ** b)
            while t < max_t:
                rungs.append(_Rung(t))
                t *= reduction_factor
            self._brackets.append(rungs)
        self._bracket_of: Dict[str, int] = {}
        self._next_bracket = 0

    def on_trial_add(self, trial: Trial):
        self._bracket_of[trial.trial_id] = (
            self._next_bracket % len(self._brackets))
        self._next_bracket += 1

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        t = result.get(self.time_attr)
        if score is None or t is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        rungs = self._brackets[self._bracket_of.get(trial.trial_id, 0)]
        action = CONTINUE
        for rung in rungs:
            if t >= rung.milestone and trial.trial_id not in getattr(
                    rung, "_seen", set()):
                seen = getattr(rung, "_seen", None)
                if seen is None:
                    rung._seen = set()
                rung._seen.add(trial.trial_id)
                cutoff = rung.cutoff(self.rf)
                rung.scores.append(score)
                if cutoff is not None and score < cutoff:
                    action = STOP
        return action


class _SyncBracket:
    """One successive-halving bracket of a HyperBand band.

    Starts ``n0`` trials at milestone ``r0``; every time all live trials
    have reported at the current milestone, keeps the top ``1/eta`` and
    multiplies the milestone by ``eta`` until it reaches ``max_t``.
    """

    def __init__(self, s: int, n0: int, r0: float, eta: float, max_t: float):
        self.s = s
        self.n0 = n0
        self.eta = eta
        self.max_t = max_t
        self.milestone = float(r0)
        self.members: List[str] = []     # all trial ids ever admitted
        self.live: set = set()           # not yet stopped/errored
        self.reported: Dict[str, float] = {}  # scores at current milestone

    def full(self) -> bool:
        return len(self.members) >= self.n0

    def add(self, trial_id: str):
        self.members.append(trial_id)
        self.live.add(trial_id)

    def cut_ready(self) -> bool:
        # A cut needs the bracket FULL as well as fully reported: trials
        # can be admitted lazily (searcher-driven), and halving over a
        # partially admitted bracket would break the exact-halving
        # contract. If admission stops early (searcher exhausted), the
        # runner's release_holds() fail-safe resolves the held trials.
        return (self.full() and bool(self.live)
                and set(self.reported) >= self.live)

    def perform_cut(self):
        """Returns (survivors, losers) and advances the milestone."""
        ranked = sorted(self.reported.items(), key=lambda kv: kv[1],
                        reverse=True)
        keep = max(1, int(math.ceil(len(ranked) / self.eta)))
        survivors = [tid for tid, _ in ranked[:keep]]
        losers = [tid for tid, _ in ranked[keep:]]
        for tid in losers:
            self.live.discard(tid)
        self.reported.clear()
        self.milestone = min(self.milestone * self.eta, self.max_t)
        return survivors, losers


class HyperBandScheduler(TrialScheduler):
    """Synchronized HyperBand (reference ``tune/schedulers/hyperband.py``).

    Bands of ``s_max+1`` brackets; bracket ``s`` admits
    ``ceil((s_max+1)/(s+1) * eta^s)`` trials starting at ``max_t / eta^s``
    iterations. Within a bracket, trials PAUSE at each milestone; when the
    last live trial reports, the bottom ``1 - 1/eta`` are terminated and
    the survivors resume toward the next milestone (successive halving).
    Unlike ASHA, cuts wait for every live trial — the original algorithm,
    which some workloads prefer for its exact halving guarantees.
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration", max_t: float = 81,
                 reduction_factor: float = 3, stop_last_trials: bool = True):
        super().__init__(metric, mode, time_attr)
        self.max_t = max_t
        self.eta = reduction_factor
        self.stop_last_trials = stop_last_trials
        self._s_max_1 = int(round(
            math.log(max_t) / math.log(reduction_factor))) + 1
        self._bands: List[List[_SyncBracket]] = []
        self._bracket_of: Dict[str, _SyncBracket] = {}
        self._held: set = set()   # paused, awaiting a band cut

    def _n0(self, s: int) -> int:
        return int(math.ceil(self._s_max_1 / (s + 1) * self.eta ** s))

    def _r0(self, s: int) -> float:
        return max(1.0, self.max_t * self.eta ** (-s))

    def _open_bracket(self) -> _SyncBracket:
        if self._bands:
            band = self._bands[-1]
            if not band[-1].full():
                return band[-1]
            if len(band) < self._s_max_1:
                s = band[-1].s - 1
                b = _SyncBracket(s, self._n0(s), self._r0(s), self.eta,
                                 self.max_t)
                band.append(b)
                return b
        # new band, starting from the most-aggressive bracket
        s = self._s_max_1 - 1
        b = _SyncBracket(s, self._n0(s), self._r0(s), self.eta, self.max_t)
        self._bands.append([b])
        return b

    def on_trial_add(self, trial: Trial):
        bracket = self._open_bracket()
        bracket.add(trial.trial_id)
        self._bracket_of[trial.trial_id] = bracket

    def may_resume(self, trial: Trial) -> bool:
        return trial.trial_id not in self._held

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        t = result.get(self.time_attr)
        bracket = self._bracket_of.get(trial.trial_id)
        if score is None or t is None or bracket is None:
            return CONTINUE
        if t >= self.max_t:
            bracket.live.discard(trial.trial_id)
            bracket.reported.pop(trial.trial_id, None)
            self._maybe_cut(bracket, exclude=trial.trial_id)
            return STOP if self.stop_last_trials else CONTINUE
        if t < bracket.milestone:
            return CONTINUE
        bracket.reported[trial.trial_id] = score
        if bracket.cut_ready():
            survivors, losers = bracket.perform_cut()
            self._apply_cut(survivors, losers, reporting=trial.trial_id)
            return STOP if trial.trial_id in losers else PAUSE
        self._held.add(trial.trial_id)
        return PAUSE

    def _apply_cut(self, survivors: List[str], losers: List[str],
                   reporting: Optional[str] = None):
        for tid in survivors:
            self._held.discard(tid)
        for tid in losers:
            self._held.discard(tid)
            if tid == reporting:
                continue  # runner stops it via the returned STOP
            if self._runner is not None:
                paused = self._runner._trial_by_id(tid)
                if paused is not None:
                    self._runner.terminate_trial(paused)

    def _drop(self, trial: Trial):
        bracket = self._bracket_of.get(trial.trial_id)
        if bracket is None:
            return
        bracket.live.discard(trial.trial_id)
        bracket.reported.pop(trial.trial_id, None)
        self._held.discard(trial.trial_id)
        self._maybe_cut(bracket, exclude=trial.trial_id)

    def _maybe_cut(self, bracket: _SyncBracket, exclude: Optional[str] = None):
        """A departure can leave the bracket cut-ready; fire the cut so the
        remaining paused trials are not held forever."""
        if bracket.cut_ready():
            survivors, losers = bracket.perform_cut()
            self._apply_cut(survivors, losers, reporting=exclude)

    def release_holds(self):
        """Force a cut from whatever has reported so far (invariant says
        cut_ready fires when the last live trial reports, so reaching this
        means some trial departed without bookkeeping — fail safe)."""
        for band in self._bands:
            for bracket in band:
                if bracket.reported:
                    survivors, losers = bracket.perform_cut()
                    self._apply_cut(survivors, losers)

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]):
        self._drop(trial)

    def on_trial_error(self, trial: Trial):
        self._drop(trial)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score so far is below the median of the
    running averages of other trials at the same time step
    (reference ``median_stopping_rule.py``)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 grace_period: float = 1, min_samples_required: int = 3):
        super().__init__(metric, mode, time_attr)
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        t = result.get(self.time_attr, 0)
        if score is None:
            return CONTINUE
        self._avgs.setdefault(trial.trial_id, []).append(score)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(v) / len(v) for k, v in self._avgs.items()
                  if k != trial.trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(self._avgs[trial.trial_id])
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference ``pbt.py``): every ``perturbation_interval`` time
    units, a bottom-quantile trial clones the checkpoint + config of a
    top-quantile trial and perturbs hyperparameters in
    ``hyperparam_mutations``."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 perturbation_interval: float = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode, time_attr)
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._last_perturb: Dict[str, float] = {}
        self._latest_score: Dict[str, float] = {}
        self._rng = random.Random(seed)
        # set by the runner so exploit can clone checkpoints
        self._runner = None

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        t = result.get(self.time_attr, 0)
        if score is not None:
            self._latest_score[trial.trial_id] = score
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._latest_score) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._latest_score.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and self._runner is not None:
            donor_id = self._rng.choice(top)
            if donor_id != trial.trial_id:
                self._exploit(trial, donor_id)
        return CONTINUE

    def _exploit(self, trial: Trial, donor_id: str):
        runner = self._runner
        donor = runner._trial_by_id(donor_id)
        if donor is None or donor.checkpoint is None:
            return
        new_config = dict(donor.config)
        for key, spec in self.mutations.items():
            new_config[key] = self._perturb(new_config.get(key), spec)
        runner._exploit_trial(trial, donor, new_config)

    def _perturb(self, current: Any, spec: Any) -> Any:
        resample = current is None or self._rng.random() < self.resample_prob
        if isinstance(spec, Domain):
            return spec.sample(self._rng)
        if isinstance(spec, list):
            if resample or current not in spec:
                return self._rng.choice(spec)
            i = spec.index(current)
            i += self._rng.choice([-1, 1])
            return spec[max(0, min(len(spec) - 1, i))]
        if callable(spec):
            return spec()
        if isinstance(current, (int, float)):
            factor = self._rng.choice([0.8, 1.2])
            v = current * factor
            return int(v) if isinstance(current, int) else v
        return current
