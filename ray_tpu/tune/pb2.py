"""PB2: Population Based Bandits.

Parity with ``python/ray/tune/schedulers/pb2.py`` (+ ``pb2_utils.py``),
re-implemented on numpy instead of the reference's GPy dependency.

PB2 (Parker-Holder et al. 2020) keeps PBT's exploit step (bottom-quantile
trials clone a top performer's checkpoint) but replaces the random
perturbation of the explore step with a GP bandit: a Gaussian process is
fit on ``(time, hyperparameters) -> score improvement`` observations from
the whole population, and the next configuration is chosen by maximizing
the UCB acquisition over the bounded hyperparameter box. This gives
provable regret bounds where PBT's random explore can thrash.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers import PopulationBasedTraining
from ray_tpu.tune.trial import Trial


class _TinyGP:
    """RBF-kernel GP regression, just enough for UCB over a box.

    The reference leans on GPy for the same few lines of algebra
    (``pb2_utils.py:normalize/optimize_acq``); zero-dependency here.
    """

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-3):
        self.ls = lengthscale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray) -> bool:
        if len(X) < 2:
            return False
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        K = self._k(X, X) + self.noise * np.eye(len(X))
        try:
            self._L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return False
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))
        self._X = X
        return True

    def predict(self, Xq: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._k(Xq, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return (mu * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


class PB2(PopulationBasedTraining):
    """Population Based Bandits scheduler.

    ``hyperparam_bounds``: dict of name -> ``[min, max]`` (continuous
    box, PB2's domain — categoricals stay with plain PBT). Exploit is
    inherited from PBT; explore fits the GP and picks the UCB argmax.
    """

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 perturbation_interval: float = 5,
                 hyperparam_bounds: Optional[Dict[str, List[float]]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 n_candidates: int = 64,
                 max_history: int = 256,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        super().__init__(metric, mode, time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={
                             k: list(v) for k, v in hyperparam_bounds.items()
                         },
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(v[0]), float(v[1]))
                       for k, v in hyperparam_bounds.items()}
        self._keys = sorted(self.bounds)
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self.max_history = max_history
        self._np_rng = np.random.default_rng(seed)
        # (t, config_vec) -> observed score improvement since the trial's
        # previous report: the GP's training data.
        self._data: List[Tuple[float, np.ndarray, float]] = []
        self._prev: Dict[str, Tuple[float, float]] = {}  # tid -> (t, score)
        self._t_max = 1.0

    # -- data collection -------------------------------------------------
    def _param_vec(self, config: Dict[str, Any]) -> np.ndarray:
        """Box-normalized hyperparameters only; the time feature is scaled
        AT FIT TIME from the stored raw t — normalizing it at append time
        with the then-current _t_max would leave every row on a different
        scale as training progresses."""
        vec = []
        for k in self._keys:
            lo, hi = self.bounds[k]
            x = float(config.get(k, lo))
            vec.append((x - lo) / ((hi - lo) or 1.0))
        return np.array(vec)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        t = float(result.get(self.time_attr, 0) or 0)
        if score is not None:
            self._t_max = max(self._t_max, t)
            prev = self._prev.get(trial.trial_id)
            if prev is not None and t > prev[0]:
                gain = (score - prev[1]) / (t - prev[0])
                self._data.append(
                    (prev[0], self._param_vec(trial.config), gain))
                if len(self._data) > self.max_history:
                    self._data = self._data[-self.max_history:]
            self._prev[trial.trial_id] = (t, score)
        return super().on_trial_result(trial, result)

    # -- explore (replaces PBT's random perturb) -------------------------
    def _exploit(self, trial: Trial, donor_id: str):
        runner = self._runner
        donor = runner._trial_by_id(donor_id)
        if donor is None or donor.checkpoint is None:
            return
        new_config = dict(donor.config)
        new_config.update(self._select_config(donor.config))
        runner._exploit_trial(trial, donor, new_config)

    def _select_config(self, base: Dict[str, Any]) -> Dict[str, Any]:
        t_now = max(v[0] for v in self._prev.values()) if self._prev else 0.0
        X = y = None
        if self._data:
            tscale = self._t_max or 1.0
            X = np.array([[t / tscale, *v] for t, v, _ in self._data])
            y = np.array([g for _, _, g in self._data])
        gp = _TinyGP()
        # Candidate set: random box samples + jittered copies of the
        # donor's point (local exploration around a known-good config).
        n = self.n_candidates
        cand = self._np_rng.random((n, len(self._keys)))
        base_vec = self._param_vec(base)
        jitter = np.clip(
            base_vec + self._np_rng.normal(0, 0.1, (n // 4, len(self._keys))),
            0.0, 1.0)
        cand = np.vstack([cand, jitter])
        if X is not None and gp.fit(X, y):
            tq = np.full((len(cand), 1), t_now / (self._t_max or 1.0))
            mu, sigma = gp.predict(np.hstack([tq, cand]))
            best = cand[int(np.argmax(mu + self.kappa * sigma))]
        else:
            best = cand[self._np_rng.integers(len(cand))]
        out: Dict[str, Any] = {}
        for i, k in enumerate(self._keys):
            lo, hi = self.bounds[k]
            v = lo + float(best[i]) * (hi - lo)
            if isinstance(base.get(k), int):
                v = int(round(v))
            out[k] = v
        return out
