"""Tree-structured Parzen Estimator searcher.

A native Bayesian searcher filling the role of the reference's external
adapters (``python/ray/tune/search/optuna/optuna_search.py``,
``hyperopt/hyperopt_search.py``) without their dependencies — the
algorithm itself (Bergstra et al. 2011, the sampler behind both Optuna's
``TPESampler`` and hyperopt's ``tpe.suggest``):

- The first ``n_initial_points`` suggestions are random (space-filling).
- After that, observations are split at the ``gamma`` quantile into good
  (l) and bad (g) sets; each dimension gets a 1-D Parzen (kernel-density)
  estimator per set. Candidates are drawn from l and the one maximizing
  the acquisition ratio ``l(x)/g(x)`` — monotone in expected improvement
  under the TPE factorization — is suggested.
- Dimensions are modeled independently (the classic TPE factorization).
  Numeric dims use truncated-Gaussian mixtures (in log space for ``log``
  domains); categoricals use smoothed category frequencies.

Grid axes (``tune.grid_search``) are treated as categorical dimensions so
any space accepted by ``BasicVariantGenerator`` works here too.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.sample import (Categorical, Domain, Float, Integer,
                                 Quantized, _is_grid)
from ray_tpu.tune.search import Searcher, _set_path, _walk


class _NumericDim:
    """Parzen-estimator dimension over a bounded numeric domain."""

    def __init__(self, lower: float, upper: float, log: bool,
                 integer: bool, q: Optional[float] = None):
        self.log = log
        self.integer = integer
        self.q = q
        if log:
            self.lo, self.hi = math.log(lower), math.log(upper)
        else:
            self.lo, self.hi = float(lower), float(upper)

    # latent <-> native -------------------------------------------------
    def to_latent(self, x: Any) -> float:
        x = float(x)
        return math.log(x) if self.log else x

    def to_native(self, z: float) -> Any:
        z = min(max(z, self.lo), self.hi)
        x = math.exp(z) if self.log else z
        if self.q:
            x = round(x / self.q) * self.q
        if self.integer:
            x = int(round(x))
        return x

    def random(self, rng: random.Random) -> float:
        return rng.uniform(self.lo, self.hi)

    # Parzen machinery ---------------------------------------------------
    def _bandwidths(self, pts: List[float]) -> List[float]:
        """Per-point bandwidth: distance to the farther sorted neighbor
        (hyperopt's heuristic), clipped so no kernel collapses or covers
        the whole range."""
        rng_width = self.hi - self.lo or 1.0
        if len(pts) == 1:
            return [rng_width / 2.0]
        order = sorted(range(len(pts)), key=lambda i: pts[i])
        bows = [0.0] * len(pts)
        for rank, i in enumerate(order):
            left = pts[order[rank - 1]] if rank > 0 else None
            right = pts[order[rank + 1]] if rank + 1 < len(order) else None
            cands = [abs(pts[i] - n) for n in (left, right) if n is not None]
            bows[i] = max(cands) if cands else rng_width / 2.0
        lo_bw = rng_width / min(100.0, 10.0 * len(pts) + 1)
        return [min(max(b, lo_bw), rng_width) for b in bows]

    def _logpdf(self, z: float, pts: List[float], bws: List[float]) -> float:
        """Mixture of the observation kernels plus ONE uniform-prior
        component (hyperopt's adaptive-Parzen construction) — the prior
        keeps densities positive everywhere and stops the estimator from
        collapsing when all observations coincide."""
        width = self.hi - self.lo or 1.0
        acc = 1.0 / width  # prior component
        for mu, bw in zip(pts, bws):
            t = (z - mu) / bw
            acc += math.exp(-0.5 * t * t) / (bw * math.sqrt(2 * math.pi))
        return math.log(acc / (len(pts) + 1))

    def propose(self, good: List[Any], bad: List[Any], n_candidates: int,
                rng: random.Random) -> Any:
        gpts = [self.to_latent(x) for x in good]
        bpts = [self.to_latent(x) for x in bad]
        gbw = self._bandwidths(gpts)
        bbw = self._bandwidths(bpts)
        best_z, best_score = None, -math.inf
        for _ in range(n_candidates):
            # draw from l including its prior component, so exploration
            # never dies even when the good set has collapsed to a point
            i = rng.randrange(len(gpts) + 1)
            if i < len(gpts):
                z = min(max(rng.gauss(gpts[i], gbw[i]), self.lo), self.hi)
            else:
                z = self.random(rng)
            score = (self._logpdf(z, gpts, gbw) -
                     self._logpdf(z, bpts, bbw))
            if score > best_score:
                best_z, best_score = z, score
        return self.to_native(best_z if best_z is not None
                              else self.random(rng))


class _CategoricalDim:
    """Smoothed-frequency dimension over a fixed category list."""

    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def _weights(self, observed: List[Any]) -> List[float]:
        counts = [1.0] * len(self.categories)  # +1 smoothing prior
        for x in observed:
            try:
                counts[self.categories.index(x)] += 1.0
            except ValueError:
                pass
        total = sum(counts)
        return [c / total for c in counts]

    def propose(self, good: List[Any], bad: List[Any], n_candidates: int,
                rng: random.Random) -> Any:
        wl = self._weights(good)
        wg = self._weights(bad)
        best_i = max(range(len(self.categories)),
                     key=lambda i: math.log(wl[i]) - math.log(wg[i]) +
                     1e-9 * rng.random())
        # sample from l but bias toward the best ratio: draw a few from l,
        # keep the max-ratio draw
        draws = rng.choices(range(len(self.categories)), weights=wl,
                            k=max(1, n_candidates // 4))
        draws.append(best_i)
        pick = max(draws, key=lambda i: math.log(wl[i]) - math.log(wg[i]))
        return self.categories[pick]


class TPESearcher(Searcher):
    """Bayesian search via Tree-structured Parzen Estimators.

    Drop-in ``Searcher``: pass as ``search_alg=`` to ``tune.run`` /
    ``Tuner`` with a space of ``tune.uniform/loguniform/randint/choice/
    grid_search`` values.
    """

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 num_samples: int = 32,
                 n_initial_points: int = 10, gamma: float = 0.15,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._rng = random.Random(seed)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._budget = num_samples
        self._suggested = 0
        self._dims: List[Tuple[Tuple, Any]] = []       # (path, dim model)
        self._passthrough: List[Tuple[Tuple, Any]] = []  # (path, const/fn)
        self._obs: List[Tuple[Dict[Tuple, Any], float]] = []
        self._pending: Dict[str, Dict[Tuple, Any]] = {}
        if space:
            self._compile(space)

    # -- space ----------------------------------------------------------
    def set_space(self, space: Optional[Dict[str, Any]],
                  num_samples: Optional[int] = None):
        """None leaves the corresponding constructor value in place."""
        if num_samples is not None:
            self._budget = num_samples
        if space:
            self._compile(space)

    def _compile(self, space: Dict[str, Any]):
        self._dims, self._passthrough = [], []
        for path, v in _walk(space):
            if _is_grid(v):
                self._dims.append((path, _CategoricalDim(v["grid_search"])))
            elif isinstance(v, Quantized):
                inner = v.inner
                # Integer domains are upper-EXCLUSIVE (randint semantics);
                # model the inclusive range [lower, upper-1] so TPE never
                # suggests a value random search could not produce
                upper = (inner.upper - 1 if isinstance(inner, Integer)
                         else inner.upper)
                self._dims.append((path, _NumericDim(
                    inner.lower, upper, getattr(inner, "log", False),
                    isinstance(inner, Integer), q=v.q)))
            elif isinstance(v, Float):
                self._dims.append((path, _NumericDim(
                    v.lower, v.upper, v.log, integer=False)))
            elif isinstance(v, Integer):
                self._dims.append((path, _NumericDim(
                    v.lower, v.upper - 1, v.log, integer=True)))
            elif isinstance(v, Categorical):
                self._dims.append((path, _CategoricalDim(v.categories)))
            else:
                # unbounded/opaque domains (Normal, Function, ...) are
                # sampled but not modeled; constants pass straight through
                self._passthrough.append((path, v))

    # -- suggest --------------------------------------------------------
    def _model_split(self):
        """(good, bad) observation lists to fit the proposal on, or None
        to sample randomly. The overridable seam for multi-fidelity
        variants (BOHB picks its budget bucket here)."""
        if len(self._obs) >= max(self.n_initial, 2):
            return self._split()
        return None

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._budget:
            return None
        self._suggested += 1
        flat: Dict[Tuple, Any] = {}
        split = self._model_split()
        model_ready = split is not None
        good_obs, bad_obs = split if model_ready else ([], [])
        for path, dim in self._dims:
            if model_ready:
                good = [o[path] for o, _ in good_obs if path in o]
                bad = [o[path] for o, _ in bad_obs if path in o]
                flat[path] = dim.propose(good, bad, self.n_candidates,
                                         self._rng)
            elif isinstance(dim, _NumericDim):
                flat[path] = dim.to_native(dim.random(self._rng))
            else:
                flat[path] = self._rng.choice(dim.categories)
        cfg: Dict[str, Any] = {}
        for path, val in flat.items():
            _set_path(cfg, path, val)
        for path, v in self._passthrough:
            _set_path(cfg, path,
                      v.sample(self._rng) if isinstance(v, Domain) else v)
        self._pending[trial_id] = flat
        return cfg

    def _split(self):
        """Split observations at the gamma quantile (higher = better
        internally; mode is normalized in on_trial_complete)."""
        ranked = sorted(self._obs, key=lambda ov: ov[1], reverse=True)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    # -- observe --------------------------------------------------------
    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        flat = self._pending.pop(trial_id, None)
        if flat is None or error or not result:
            return
        metric = self.metric
        if metric is None or metric not in result:
            return
        v = float(result[metric])
        self._obs.append((flat, -v if self.mode == "min" else v))
