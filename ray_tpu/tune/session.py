"""In-trial session API: ``tune.report`` / ``tune.get_checkpoint``.

Parity with the reference's ``ray.tune.report`` routed through
``air/session.py`` into the function trainable's reporter queue.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_local = threading.local()


def _init_session(trainable):
    _local.trainable = trainable


def _shutdown_session():
    _local.trainable = None


def _get() -> Optional[Any]:
    return getattr(_local, "trainable", None)


def report(metrics: Optional[Dict[str, Any]] = None,
           checkpoint: Optional[Dict[str, Any]] = None, **kwargs):
    """Report metrics (and optionally a checkpoint dict) from inside a
    function trainable. Accepts both ``report({...})`` and
    ``report(loss=..)`` forms like the reference."""
    t = _get()
    m = dict(metrics or {})
    m.update(kwargs)
    if t is None:
        # Running outside tune (e.g. the bare function called directly):
        # no-op, matching reference behavior of session-less report.
        return
    t._report(m, checkpoint)


def get_checkpoint() -> Optional[Dict[str, Any]]:
    t = _get()
    if t is None:
        return None
    return t._get_checkpoint()


def get_trial_id() -> Optional[str]:
    t = _get()
    return getattr(t, "_trial_id", None) if t is not None else None
