"""Experiment analysis / result grid.

Parity with ``python/ray/tune/analysis/experiment_analysis.py`` and the
``ResultGrid`` returned by ``Tuner.fit`` (``tune/result_grid.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.trial import ERROR, TERMINATED, Trial


class ExperimentAnalysis:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self.trials = trials
        self.default_metric = metric
        self.default_mode = mode

    def get_best_trial(self, metric: Optional[str] = None,
                       mode: Optional[str] = None,
                       scope: str = "last") -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        sign = 1 if mode == "max" else -1

        def score(t: Trial) -> float:
            vals = t.metric_history(metric)
            if not vals:
                return float("-inf")
            if scope == "last":
                return sign * vals[-1]
            if scope == "avg":
                return sign * sum(vals) / len(vals)
            return sign * max(sign * v for v in vals)  # "all": best ever

        candidates = [t for t in self.trials if t.metric_history(metric or "")]
        if not candidates:
            return None
        return max(candidates, key=score)

    def get_best_config(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Optional[Dict]:
        t = self.get_best_trial(metric, mode)
        return t.config if t else None

    def get_best_checkpoint(self, metric: Optional[str] = None,
                            mode: Optional[str] = None):
        t = self.get_best_trial(metric, mode)
        return t.checkpoint if t else None

    @property
    def best_trial(self) -> Optional[Trial]:
        return self.get_best_trial()

    @property
    def best_config(self) -> Optional[Dict]:
        return self.get_best_config()

    @property
    def best_result(self) -> Optional[Dict]:
        t = self.get_best_trial()
        return t.last_result if t else None

    def dataframe(self):
        import pandas as pd
        rows = []
        for t in self.trials:
            row = dict(t.last_result)
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)

    @property
    def results(self) -> Dict[str, Dict]:
        return {t.trial_id: t.last_result for t in self.trials}


class ResultGrid:
    """Tuner.fit() return value (reference ``tune/result_grid.py``)."""

    def __init__(self, analysis: ExperimentAnalysis):
        self._analysis = analysis

    def __len__(self):
        return len(self._analysis.trials)

    def __getitem__(self, i: int):
        t = self._analysis.trials[i]
        from ray_tpu.air.config import Result
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, metrics_history=t.results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None):
        t = self._analysis.get_best_trial(metric, mode)
        if t is None:
            return None
        from ray_tpu.air.config import Result
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, metrics_history=t.results)

    def get_dataframe(self):
        return self._analysis.dataframe()

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._analysis.trials if t.status == ERROR]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._analysis.trials
                   if t.status == TERMINATED)
