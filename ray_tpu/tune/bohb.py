"""BOHB: Bayesian-Optimization HyperBand.

Parity with the reference's BOHB pair — ``TuneBOHB``
(``python/ray/tune/search/bohb/bohb_search.py``, an HpBandSter wrapper)
plus ``HyperBandForBOHB`` (``python/ray/tune/schedulers/hb_bohb.py``) —
re-implemented natively on this package's TPE machinery instead of an
external dependency, exactly as ``tpe.py`` replaces Optuna/hyperopt
(Falkner et al. 2018: HyperBand for budget allocation, a TPE/KDE model
fit per budget for config selection).

Multi-fidelity rule (the BOHB paper's): observations are bucketed by the
budget (``time_attr`` value) they were measured at; the model for the
next suggestion is fit on the LARGEST budget that has at least
``min_points_in_model`` observations — results from cheap rungs guide
early, and get superseded by full-budget evidence as it accumulates.
With probability ``random_fraction`` a configuration is sampled at
random instead (keeps the bandit honest, per the paper).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.sample import (Categorical, Domain, Float, Integer,
                                 Quantized, _is_grid)
from ray_tpu.tune.schedulers import HyperBandScheduler
from ray_tpu.tune.search import Searcher, _set_path, _walk
from ray_tpu.tune.tpe import _CategoricalDim, _NumericDim


class BOHBSearcher(Searcher):
    """Model-based searcher for HyperBand-style multi-fidelity runs.

    Use with ``HyperBandForBOHB`` (or any banded scheduler): the runner
    feeds every intermediate result through ``on_trial_result``, which is
    where the per-budget observation sets are built — completion-only
    feedback would discard exactly the low-budget evidence BOHB exists to
    exploit.
    """

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 num_samples: int = 64,
                 time_attr: str = "training_iteration",
                 min_points_in_model: int = 6,
                 gamma: float = 0.25, n_candidates: int = 24,
                 random_fraction: float = 1.0 / 3.0,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.min_points = min_points_in_model
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.random_fraction = random_fraction
        self._rng = random.Random(seed)
        self._budget = num_samples
        self._suggested = 0
        self._dims: List[Tuple[Tuple, Any]] = []
        self._passthrough: List[Tuple[Tuple, Any]] = []
        # budget -> list of (flat_config, score); scores normalized to
        # higher-is-better.
        self._obs_by_budget: Dict[float, List[Tuple[Dict, float]]] = {}
        self._pending: Dict[str, Dict[Tuple, Any]] = {}
        if space:
            self._compile(space)

    # -- space (same compilation rules as TPESearcher) -------------------
    def set_space(self, space: Optional[Dict[str, Any]],
                  num_samples: Optional[int] = None):
        if num_samples is not None:
            self._budget = num_samples
        if space:
            self._compile(space)

    def _compile(self, space: Dict[str, Any]):
        self._dims, self._passthrough = [], []
        for path, v in _walk(space):
            if _is_grid(v):
                self._dims.append((path, _CategoricalDim(v["grid_search"])))
            elif isinstance(v, Quantized):
                inner = v.inner
                upper = (inner.upper - 1 if isinstance(inner, Integer)
                         else inner.upper)
                self._dims.append((path, _NumericDim(
                    inner.lower, upper, getattr(inner, "log", False),
                    isinstance(inner, Integer), q=v.q)))
            elif isinstance(v, Float):
                self._dims.append((path, _NumericDim(
                    v.lower, v.upper, v.log, integer=False)))
            elif isinstance(v, Integer):
                self._dims.append((path, _NumericDim(
                    v.lower, v.upper - 1, v.log, integer=True)))
            elif isinstance(v, Categorical):
                self._dims.append((path, _CategoricalDim(v.categories)))
            else:
                self._passthrough.append((path, v))

    # -- model selection -------------------------------------------------
    def _model_obs(self) -> Optional[List[Tuple[Dict, float]]]:
        """Observations at the largest budget with enough points."""
        for budget in sorted(self._obs_by_budget, reverse=True):
            obs = self._obs_by_budget[budget]
            if len(obs) >= max(self.min_points, 2):
                return obs
        return None

    def _split(self, obs: List[Tuple[Dict, float]]):
        ranked = sorted(obs, key=lambda ov: ov[1], reverse=True)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    # -- suggest ---------------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._budget:
            return None
        self._suggested += 1
        obs = self._model_obs()
        use_model = (obs is not None
                     and self._rng.random() >= self.random_fraction)
        good_obs, bad_obs = self._split(obs) if use_model else ([], [])
        flat: Dict[Tuple, Any] = {}
        for path, dim in self._dims:
            if use_model:
                good = [o[path] for o, _ in good_obs if path in o]
                bad = [o[path] for o, _ in bad_obs if path in o]
                flat[path] = dim.propose(good, bad, self.n_candidates,
                                         self._rng)
            elif isinstance(dim, _NumericDim):
                flat[path] = dim.to_native(dim.random(self._rng))
            else:
                flat[path] = self._rng.choice(dim.categories)
        cfg: Dict[str, Any] = {}
        for path, val in flat.items():
            _set_path(cfg, path, val)
        for path, v in self._passthrough:
            _set_path(cfg, path,
                      v.sample(self._rng) if isinstance(v, Domain) else v)
        self._pending[trial_id] = flat
        return cfg

    # -- observe ---------------------------------------------------------
    def _record(self, trial_id: str, result: Dict[str, Any]):
        flat = self._pending.get(trial_id)
        if flat is None or not result:
            return
        if self.metric is None or self.metric not in result:
            return
        budget = float(result.get(self.time_attr, 0) or 0)
        v = float(result[self.metric])
        score = -v if self.mode == "min" else v
        bucket = self._obs_by_budget.setdefault(budget, [])
        # One observation per (trial, budget): a trial re-reporting at the
        # same budget (e.g. unchanged time_attr) replaces its entry.
        for i, (o, _) in enumerate(bucket):
            if o is flat:
                bucket[i] = (flat, score)
                return
        bucket.append((flat, score))

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        if not error and result:
            self._record(trial_id, result)
        self._pending.pop(trial_id, None)


class HyperBandForBOHB(HyperBandScheduler):
    """Banded HyperBand paired with ``BOHBSearcher``
    (``python/ray/tune/schedulers/hb_bohb.py`` role).

    The synchronous band machinery is inherited unchanged: rung cutoffs
    define the budgets at which trials report, and those intermediate
    reports reach the searcher through the runner's per-result hook — no
    scheduler-to-searcher coupling is needed here (the reference couples
    them only because HpBandSter owns both halves in-process).
    """
