"""BOHB: Bayesian-Optimization HyperBand.

Parity with the reference's BOHB pair — ``TuneBOHB``
(``python/ray/tune/search/bohb/bohb_search.py``, an HpBandSter wrapper)
plus ``HyperBandForBOHB`` (``python/ray/tune/schedulers/hb_bohb.py``) —
re-implemented natively on this package's TPE machinery instead of an
external dependency, exactly as ``tpe.py`` replaces Optuna/hyperopt
(Falkner et al. 2018: HyperBand for budget allocation, a TPE/KDE model
fit per budget for config selection).

Multi-fidelity rule (the BOHB paper's): observations are bucketed by the
budget (``time_attr`` value) they were measured at; the model for the
next suggestion is fit on the LARGEST budget that has at least
``min_points_in_model`` observations — results from cheap rungs guide
early, and get superseded by full-budget evidence as it accumulates.
With probability ``random_fraction`` a configuration is sampled at
random instead (keeps the bandit honest, per the paper).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.schedulers import HyperBandScheduler
from ray_tpu.tune.tpe import TPESearcher


class BOHBSearcher(TPESearcher):
    """Model-based searcher for HyperBand-style multi-fidelity runs.

    Space compilation, Parzen proposal machinery, and the suggest loop
    are inherited from :class:`TPESearcher`; only observation management
    (per-budget buckets) and model selection differ. Use with
    ``HyperBandForBOHB`` (or any banded scheduler): the runner feeds
    every intermediate result through ``on_trial_result``, which is
    where the per-budget observation sets are built — completion-only
    feedback would discard exactly the low-budget evidence BOHB exists
    to exploit.
    """

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 num_samples: int = 64,
                 time_attr: str = "training_iteration",
                 min_points_in_model: int = 6,
                 gamma: float = 0.25, n_candidates: int = 24,
                 random_fraction: float = 1.0 / 3.0,
                 seed: Optional[int] = None):
        super().__init__(space, metric, mode, num_samples=num_samples,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        self.time_attr = time_attr
        self.min_points = min_points_in_model
        self.random_fraction = random_fraction
        # budget -> list of (flat_config, score); scores normalized to
        # higher-is-better.
        self._obs_by_budget: Dict[float, List[Tuple[Dict, float]]] = {}

    # -- model selection (the TPESearcher seam) --------------------------
    def _model_obs(self) -> Optional[List[Tuple[Dict, float]]]:
        """Observations at the largest budget with enough points."""
        for budget in sorted(self._obs_by_budget, reverse=True):
            obs = self._obs_by_budget[budget]
            if len(obs) >= max(self.min_points, 2):
                return obs
        return None

    def _model_split(self):
        obs = self._model_obs()
        if obs is None or self._rng.random() < self.random_fraction:
            return None
        ranked = sorted(obs, key=lambda ov: ov[1], reverse=True)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    # -- observe ---------------------------------------------------------
    def _record(self, trial_id: str, result: Dict[str, Any]):
        flat = self._pending.get(trial_id)
        if flat is None or not result:
            return
        if self.metric is None or self.metric not in result:
            return
        budget = float(result.get(self.time_attr, 0) or 0)
        v = float(result[self.metric])
        score = -v if self.mode == "min" else v
        bucket = self._obs_by_budget.setdefault(budget, [])
        # One observation per (trial, budget): a trial re-reporting at the
        # same budget (e.g. unchanged time_attr) replaces its entry.
        for i, (o, _) in enumerate(bucket):
            if o is flat:
                bucket[i] = (flat, score)
                return
        bucket.append((flat, score))

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        if not error and result:
            self._record(trial_id, result)
        self._pending.pop(trial_id, None)


class HyperBandForBOHB(HyperBandScheduler):
    """Banded HyperBand paired with ``BOHBSearcher``
    (``python/ray/tune/schedulers/hb_bohb.py`` role).

    The synchronous band machinery is inherited unchanged: rung cutoffs
    define the budgets at which trials report, and those intermediate
    reports reach the searcher through the runner's per-result hook — no
    scheduler-to-searcher coupling is needed here (the reference couples
    them only because HpBandSter owns both halves in-process).
    """
