"""Trial execution: actor-per-trial event loop.

Parity with ``python/ray/tune/execution/trial_runner.py`` (``TrialRunner.step``
:234,853) and ``ray_trial_executor.py``: each trial runs as a ``ray_tpu``
actor; the driver loop starts pending trials up to the resource-derived
concurrency cap, waits on in-flight ``train()`` futures, routes results
through the scheduler (CONTINUE/PAUSE/STOP), checkpoints trials, restarts
failed trials from their last checkpoint up to ``max_failures``, and
persists experiment state for resume (``trial_runner.py:671,1240``).
"""

from __future__ import annotations
import logging

import json
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial import (ERROR, PAUSED, PENDING, RUNNING, TERMINATED,
                                Trial)

logger = logging.getLogger("ray_tpu")


@ray_tpu.remote
class _TrainableActor:
    """Hosts one Trainable instance (the executor's trial actor)."""

    def __init__(self, trainable_cls_bytes: bytes, config: Dict[str, Any],
                 logdir: str, trial_id: str):
        import cloudpickle
        cls = cloudpickle.loads(trainable_cls_bytes)
        self._t = cls(config, logdir)
        self._t._trial_id = trial_id

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self) -> Dict[str, Any]:
        return self._t.save()

    def restore(self, payload: Dict[str, Any]):
        self._t.restore(payload)

    def reset(self, new_config: Dict[str, Any]) -> bool:
        return self._t.reset(new_config)

    def stop(self):
        self._t.stop()


def _as_trainable_cls(trainable) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return wrap_function(trainable)
    raise TypeError(f"not a trainable: {trainable!r}")


class TrialRunner:
    def __init__(self, trainable, trials: List[Trial],
                 scheduler=None,
                 stop: Optional[Any] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 max_concurrent_trials: Optional[int] = None,
                 max_failures: int = 0,
                 checkpoint_freq: int = 0,
                 checkpoint_at_end: bool = False,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 callbacks: Optional[List[Any]] = None,
                 local_dir: Optional[str] = None,
                 experiment_name: str = "experiment",
                 searcher=None,
                 time_budget_s: Optional[float] = None,
                 sync_config=None):
        import cloudpickle
        self._trainable_cls = _as_trainable_cls(trainable)
        self._trainable_bytes = cloudpickle.dumps(self._trainable_cls)
        self.trials = list(trials)
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        # schedulers that act on non-reporting trials (PBT exploit,
        # HyperBand band cuts) reach the executor through this backref
        self.scheduler._runner = self
        self.searcher = searcher
        # fill in only what each searcher was not configured with — a
        # searcher built with mode="min" must not be flipped by run()'s
        # "max" default. Walk wrapper chains (ConcurrencyLimiter/Repeater)
        # so the inner searcher actually doing the learning is reached.
        s = self.searcher
        while s is not None:
            if s.metric is None:
                s.metric = metric
            if s.mode is None:
                s.mode = mode
            s = getattr(s, "searcher", None)
        self._stop = stop
        self.metric, self.mode = metric, mode
        self.max_failures = max_failures
        self.checkpoint_freq = checkpoint_freq
        self.checkpoint_at_end = checkpoint_at_end
        self.resources_per_trial = resources_per_trial or {"cpu": 1}
        self.callbacks = callbacks or []
        self.time_budget_s = time_budget_s
        self._start_time: Optional[float] = None
        self.local_dir = local_dir or os.path.expanduser(
            "~/ray_tpu_results")
        self.experiment_dir = os.path.join(self.local_dir, experiment_name)
        os.makedirs(self.experiment_dir, exist_ok=True)
        if max_concurrent_trials:
            self._max_concurrent = max_concurrent_trials
        else:
            self._max_concurrent = self._derive_concurrency()
        from ray_tpu.tune.syncer import _SyncerState
        self._syncer = _SyncerState(sync_config, self.experiment_dir,
                                    experiment_name)
        # Trial checkpoints live in one shared content-addressed store;
        # Trial.checkpoint holds a tiny picklable CheckpointRef, so
        # experiment state files and PBT exploits move manifest pointers,
        # not payload copies. Dedup makes PBT clone-heavy saves ~free.
        from ray_tpu.checkpoint import CheckpointEngine
        self._ckpt_engine = CheckpointEngine(
            os.path.join(self.experiment_dir, "checkpoint_store"))
        self._ckpt_seq = 0
        for t in self.trials:
            self.scheduler.on_trial_add(t)

    def _save_trial_checkpoint(self, trial: Trial):
        """Snapshot a trial's state into the shared engine store; returns a
        CheckpointRef pinned to the committed manifest. Synchronous: a ref
        must never circulate (PBT exploit, state files) before its commit."""
        payload = ray_tpu.get(trial._actor.save.remote())
        from ray_tpu.checkpoint import CheckpointRef
        self._ckpt_seq += 1
        handle = self._ckpt_engine.save(
            payload, step=self._ckpt_seq,
            meta={"trial_id": trial.trial_id},
            save_key=f"{trial.trial_id}-{self._ckpt_seq:08d}")
        return CheckpointRef(self._ckpt_engine.root,
                             handle.result(timeout=self._budget_left()))

    def _budget_left(self) -> Optional[float]:
        """Remaining experiment time budget, with a one-minute grace floor:
        an in-flight checkpoint commit may finish past the budget (a ref
        must never circulate uncommitted) but not hang forever."""
        if self.time_budget_s is None or not self._start_time:
            return None
        return max(60.0,
                   self.time_budget_s - (time.time() - self._start_time))

    @staticmethod
    def _resolve_checkpoint(checkpoint):
        """A trial checkpoint is a CheckpointRef (engine manifest) or, for
        backward compatibility, a raw payload dict."""
        from ray_tpu.checkpoint import CheckpointRef
        if isinstance(checkpoint, CheckpointRef):
            return checkpoint.load()
        return checkpoint

    def _derive_concurrency(self) -> int:
        try:
            avail = ray_tpu.cluster_resources()
        except Exception as e:
            logger.debug("cluster_resources unavailable; defaulting: %s", e)
            return 4
        cpus = avail.get("CPU", 4)
        per = self.resources_per_trial.get(
            "cpu", self.resources_per_trial.get("CPU", 1)) or 1
        return max(1, int(cpus / per))

    # ------------------------------------------------------------------
    def _may_resume(self, trial: Trial) -> bool:
        # getattr: duck-typed user schedulers predating may_resume()
        fn = getattr(self.scheduler, "may_resume", None)
        return True if fn is None else fn(trial)

    def _trial_by_id(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def _start_trial(self, trial: Trial, restore: bool = True):
        trial.logdir = os.path.join(self.experiment_dir, trial.trial_id)
        os.makedirs(trial.logdir, exist_ok=True)
        cpu = self.resources_per_trial.get(
            "cpu", self.resources_per_trial.get("CPU", 1))
        tpu = self.resources_per_trial.get(
            "tpu", self.resources_per_trial.get("TPU", 0))
        actor = _TrainableActor.options(
            num_cpus=cpu, num_tpus=tpu or None).remote(
                self._trainable_bytes, trial.config, trial.logdir,
                trial.trial_id)
        trial._actor = actor
        if restore and trial.checkpoint is not None:
            ray_tpu.get(actor.restore.remote(
                self._resolve_checkpoint(trial.checkpoint)))
        trial.status = RUNNING
        if trial.start_time is None:
            trial.start_time = time.time()
        trial._future = actor.train.remote()
        for cb in self.callbacks:
            cb.on_trial_start(trial)

    def _stop_trial(self, trial: Trial, status: str = TERMINATED,
                    save: bool = False):
        if trial._actor is not None:
            try:
                if save:
                    trial.checkpoint = self._save_trial_checkpoint(trial)
                ray_tpu.get(trial._actor.stop.remote())
            except Exception as e:
                logger.debug("trial save/stop failed: %s", e)
            try:
                ray_tpu.kill(trial._actor)
            except Exception as e:
                logger.debug("trial actor kill failed: %s", e)
        trial._actor = None
        trial._future = None
        trial.status = status
        for cb in self.callbacks:
            cb.on_trial_complete(trial)

    def terminate_trial(self, trial: Trial):
        """Terminate a trial on a scheduler's behalf (e.g. a HyperBand band
        cut killing a PAUSED loser). Unlike a bare ``_stop_trial`` this
        also notifies the searcher, so ConcurrencyLimiter slots are freed
        and the model sees the loser's final score."""
        if trial.status == TERMINATED:
            return
        self._stop_trial(trial, status=TERMINATED)
        if self.searcher is not None:
            self.searcher.on_trial_complete(
                trial.trial_id, trial.last_result or None)

    def _exploit_trial(self, trial: Trial, donor: Trial,
                       new_config: Dict[str, Any]):
        """PBT exploit: replace trial's state with donor's checkpoint and a
        perturbed config (reference ``pbt.py _exploit``)."""
        if trial._actor is None:
            return
        reset_ok = False
        try:
            reset_ok = ray_tpu.get(trial._actor.reset.remote(new_config))
        except Exception as e:
            logger.debug("trial reset failed; will restart: %s", e)
            reset_ok = False
        if not reset_ok:
            self._stop_trial(trial, status=PAUSED)
            trial.config = new_config
            trial.checkpoint = donor.checkpoint
            self._start_trial(trial, restore=True)
            return
        trial.config = new_config
        ray_tpu.get(trial._actor.restore.remote(
            self._resolve_checkpoint(donor.checkpoint)))
        trial.checkpoint = donor.checkpoint
        trial._future = trial._actor.train.remote()

    def _should_stop_trial(self, trial: Trial, result: Dict[str, Any]) -> bool:
        if result.get("done"):
            return True
        s = self._stop
        if s is None:
            return False
        if callable(s):
            return bool(s(trial.trial_id, result))
        if isinstance(s, dict):
            for k, v in s.items():
                if k in result and result[k] >= v:
                    return True
        return False

    def _maybe_checkpoint(self, trial: Trial, result: Dict[str, Any]):
        it = result.get("training_iteration", 0)
        if self.checkpoint_freq and it % self.checkpoint_freq == 0:
            trial.checkpoint = self._save_trial_checkpoint(trial)

    # ------------------------------------------------------------------
    def run(self):
        self._start_time = time.time()
        while True:
            if self._over_budget():
                for t in self.trials:
                    if t.status == RUNNING:
                        self._stop_trial(t, save=self.checkpoint_at_end)
                break
            self._launch_pending()
            inflight = {t._future: t for t in self.trials
                        if t.status == RUNNING and t._future is not None}
            if not inflight:
                if any(t.status == PENDING or
                       (t.status == PAUSED and self._may_resume(t))
                       for t in self.trials):
                    continue
                held = [t for t in self.trials if t.status == PAUSED]
                if held:
                    # No runnable work and every paused trial is held by
                    # the scheduler: ask it to resolve the pending
                    # synchronization; if that frees nothing, the bracket
                    # is genuinely stuck — end the experiment rather than
                    # spin or violate the concurrency cap.
                    getattr(self.scheduler, "release_holds", lambda: None)()
                    if any(t.status == PAUSED and self._may_resume(t)
                           for t in self.trials):
                        continue
                    break
                break
            ready, _ = ray_tpu.wait(list(inflight.keys()), num_returns=1,
                                    timeout=10.0)
            if not ready:
                continue
            trial = inflight[ready[0]]
            self._process_result(trial, ready[0])
            self._syncer.maybe_sync()
        self.save_experiment_state()
        self._ckpt_engine.close(timeout=5.0)
        self._syncer.maybe_sync(force=True)  # failure logged by the state
        return self.trials

    def _over_budget(self) -> bool:
        return (self.time_budget_s is not None and self._start_time and
                time.time() - self._start_time > self.time_budget_s)

    def _launch_pending(self):
        running = sum(1 for t in self.trials if t.status == RUNNING)
        for t in self.trials:
            if running >= self._max_concurrent:
                break
            if t.status == PENDING or (
                    t.status == PAUSED and self._may_resume(t)):
                self._start_trial(t)
                running += 1
        # pull more suggestions from a live searcher
        while (self.searcher is not None and
               running < self._max_concurrent):
            tid = f"trial_{len(self.trials)}"
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                break
            t = Trial(cfg, trial_id=tid)
            self.trials.append(t)
            self.scheduler.on_trial_add(t)
            self._start_trial(t)
            running += 1

    def _process_result(self, trial: Trial, future):
        try:
            result = ray_tpu.get(future)
        except Exception as e:  # trial actor failed
            trial.num_failures += 1
            trial.error = repr(e)
            self.scheduler.on_trial_error(trial)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.trial_id, error=True)
            if trial.num_failures <= self.max_failures:
                # restart from last checkpoint (trial_runner.py:1240)
                # raylint: allow(collective-divergence) trial engine is driver-local (world_size=1): save() commits without a cross-rank barrier
                self._stop_trial(trial, status=PENDING)
            else:
                # raylint: allow(collective-divergence) trial engine is driver-local (world_size=1): save() commits without a cross-rank barrier
                self._stop_trial(trial, status=ERROR)
            return
        trial.results.append(result)
        trial.last_result = result
        for cb in self.callbacks:
            cb.on_trial_result(trial, result)
        if self.searcher is not None:
            self.searcher.on_trial_result(trial.trial_id, result)
        self._maybe_checkpoint(trial, result)
        decision = self.scheduler.on_trial_result(trial, result)
        if trial.status != RUNNING or trial._future is None:
            # scheduler (e.g. PBT exploit) already restarted the trial
            return
        if self._should_stop_trial(trial, result):
            decision = STOP
        if decision == STOP:
            if self.checkpoint_at_end:
                trial.checkpoint = self._save_trial_checkpoint(trial)
            self.scheduler.on_trial_complete(trial, result)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.trial_id, result)
            self._stop_trial(trial, status=TERMINATED)
        elif decision == PAUSE:
            self._stop_trial(trial, status=PAUSED, save=True)
        else:
            trial._future = trial._actor.train.remote()

    # -- experiment persistence ----------------------------------------
    def save_experiment_state(self):
        state_path = os.path.join(self.experiment_dir, "experiment_state.json")
        ckpt_path = os.path.join(self.experiment_dir, "trial_checkpoints.pkl")
        with open(state_path, "w") as f:
            json.dump({"trials": [t.summary() for t in self.trials],
                       "timestamp": time.time()}, f, indent=2, default=repr)
        with open(ckpt_path, "wb") as f:
            pickle.dump({t.trial_id: t.checkpoint for t in self.trials}, f)

    @classmethod
    def load_experiment_state(cls, experiment_dir: str):
        with open(os.path.join(experiment_dir, "experiment_state.json")) as f:
            state = json.load(f)
        ckpts = {}
        p = os.path.join(experiment_dir, "trial_checkpoints.pkl")
        if os.path.exists(p):
            with open(p, "rb") as f:
                ckpts = pickle.load(f)
        trials = []
        for ts in state["trials"]:
            t = Trial(ts["config"], trial_id=ts["trial_id"])
            t.status = (TERMINATED if ts["status"] == TERMINATED
                        else PENDING)
            t.last_result = ts.get("last_result") or {}
            if t.last_result:
                t.results = [t.last_result]
            t.checkpoint = ckpts.get(t.trial_id)
            trials.append(t)
        return trials
