"""Cluster lifecycle CLI: ``ray-tpu start / stop / status / supervise``.

Parity with the reference's cluster commands
(``python/ray/scripts/scripts.py:532`` ``ray start --head/--address`` and
``ray stop``): ``start --head`` boots a supervised head node (C++ state
service + host daemon) and writes the cluster address to the run dir;
``start --address=`` joins a worker node; both keep a supervisor process
behind that restarts crashed children (``_private/node.py``). Drivers
connect with ``ray_tpu.init(address=...)``.

Usage:
  python -m ray_tpu.scripts.cluster start --head [--num-cpus N] [--block]
  python -m ray_tpu.scripts.cluster start --address HOST:PORT [--num-cpus N]
  python -m ray_tpu.scripts.cluster status [--run-dir DIR | --address A]
  python -m ray_tpu.scripts.cluster stop [--run-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

DEFAULT_RUN_DIR = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "cluster")


def read_address(run_dir: str = DEFAULT_RUN_DIR,
                 timeout_s: float = 0.0) -> Optional[str]:
    path = os.path.join(run_dir, "address")
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with open(path) as f:
                addr = f.read().strip()
            if addr:
                return addr
        except OSError:
            pass
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)  # raylint: allow(bare-retry) local file-appearance poll, deadline-bounded


def start(head: bool = False, address: str = "",
          num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
          resources: Optional[Dict[str, float]] = None,
          tp_cpu_devices: int = 0, run_dir: str = DEFAULT_RUN_DIR,
          heartbeat_timeout_ms: float = 5000,
          auth: bool = True, auth_token: str = "",
          block: bool = False) -> str:
    """Start a supervised node; returns the cluster (state service) address.

    ``block=False`` leaves a detached ``supervise`` process running; stop
    it with ``stop(run_dir)``.

    ``auth`` (default on) protects every daemon/state connection with a
    shared secret: the head mints one (written to ``<run_dir>/token``,
    mode 0600) unless ``auth_token``/$RAY_TPU_AUTH_TOKEN supplies it;
    workers and drivers must present the same token (reference analogue:
    the redis password every raylet/driver needs).
    """
    if head == bool(address):
        raise ValueError("pass exactly one of head=True or address=...")
    os.makedirs(run_dir, exist_ok=True)
    if os.path.exists(os.path.join(run_dir, "supervisor.pid")):
        raise RuntimeError(
            f"a node is already running from {run_dir} (stale? run stop, "
            f"or remove supervisor.pid)")
    # A crashed previous run may have left address files behind; starting
    # must never hand out a dead address.
    for stale in ("address", "daemon.addr"):
        try:
            os.unlink(os.path.join(run_dir, stale))
        except OSError:
            pass
    token = ""
    if auth:
        token = (auth_token or os.environ.get("RAY_TPU_AUTH_TOKEN", ""))
        if not token:
            if head:
                import secrets
                token = secrets.token_hex(16)
            else:
                raise ValueError(
                    "joining an authenticated cluster needs its token: pass "
                    "auth_token=, set RAY_TPU_AUTH_TOKEN, or use auth=False "
                    "for an open cluster")
        token_path = os.path.join(run_dir, "token")
        fd = os.open(token_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(token)
    if block:
        from ray_tpu._private.node import NodeSupervisor
        sup = NodeSupervisor(run_dir, head=head, state_addr=address,
                             num_cpus=num_cpus, num_tpus=num_tpus,
                             resources=resources,
                             tp_cpu_devices=tp_cpu_devices,
                             heartbeat_timeout_ms=heartbeat_timeout_ms,
                             auth_token=token)
        sup.run()  # returns on SIGTERM/SIGINT
        return read_address(run_dir) or address
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cluster", "supervise",
           "--run-dir", run_dir,
           "--heartbeat-timeout-ms", str(heartbeat_timeout_ms),
           "--resources", json.dumps(resources or {}),
           "--tp-cpu-devices", str(tp_cpu_devices)]
    if token:
        cmd += ["--token-file", os.path.join(run_dir, "token")]
    if head:
        cmd.append("--head")
    else:
        cmd += ["--address", address]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    log_path = os.path.join(run_dir, "supervisor.log")
    with open(log_path, "ab") as log:
        subprocess.Popen(cmd, stdout=log, stderr=log,
                         start_new_session=True)
    if head:
        addr = read_address(run_dir, timeout_s=60)
        if addr is None:
            raise TimeoutError(
                f"head did not publish an address (see {log_path})")
    else:
        addr = address
    # Wait for this node's daemon to come up so `start` returning means
    # the node is usable.
    deadline = time.monotonic() + 90
    daemon_addr = None
    while time.monotonic() < deadline:
        try:
            with open(os.path.join(run_dir, "daemon.addr")) as f:
                daemon_addr = f.read().strip()
            if daemon_addr:
                break
        except OSError:
            time.sleep(0.1)  # raylint: allow(bare-retry) local file-appearance poll, deadline-bounded
    if not daemon_addr:
        raise TimeoutError(f"daemon did not start (see {log_path})")
    return addr


def _running(pid: int) -> bool:
    """Alive and not a zombie (an unreaped supervisor child of the caller
    keeps its pid; /proc state tells the truth)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state != "Z"
    except OSError:
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False


def stop(run_dir: str = DEFAULT_RUN_DIR, timeout_s: float = 15.0) -> bool:
    """SIGTERM the supervisor (which tears its children down)."""
    pid_path = os.path.join(run_dir, "supervisor.pid")
    try:
        with open(pid_path) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return False
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        os.unlink(pid_path)
        return False
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if not _running(pid):
            return True
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    return True


def status(address: Optional[str] = None,
           run_dir: str = DEFAULT_RUN_DIR) -> Dict:
    addr = address or read_address(run_dir)
    if addr is None:
        raise RuntimeError(f"no cluster address (run dir {run_dir})")
    # LOCAL cluster (addr from run_dir): its token file is authoritative.
    # An explicit address may be a different cluster — never assume the
    # local token, and never mutate process env from a status query.
    token = None
    token_path = os.path.join(run_dir, "token")
    if address is None and os.path.exists(token_path):
        with open(token_path) as f:
            token = f.read().strip().encode()
    from ray_tpu._private.state_client import StateClient
    client = StateClient(addr, auth_token=token)
    try:
        nodes = client.list_nodes()
        out = {"address": addr, "nodes": []}
        for n in nodes:
            out["nodes"].append({
                "node_id": n.node_id.hex()[:16],
                "address": n.address,
                "alive": n.alive,
                "is_head": n.is_head,
                "total": dict(n.total.amounts),
                "available": dict(n.available.amounts),
            })
        return out
    finally:
        client.close()


# -- CLI ---------------------------------------------------------------------


def _read_token(path: str) -> str:
    """Token files are written with a trailing newline; strip the way the
    C++ state service does (leading/trailing whitespace)."""
    if not path:
        return ""
    with open(path) as f:
        return f.read().strip()


def _cmd_start(args):
    token = _read_token(args.token_file)
    addr = start(head=args.head, address=args.address or "",
                 num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                 resources=json.loads(args.resources),
                 tp_cpu_devices=args.tp_cpu_devices,
                 run_dir=args.run_dir,
                 heartbeat_timeout_ms=args.heartbeat_timeout_ms,
                 auth=not args.no_auth, auth_token=token,
                 block=args.block)
    print(f"ray_tpu node up; cluster address: {addr}")
    if not args.no_auth:
        print(f"auth token: {os.path.join(args.run_dir, 'token')} "
              f"(workers/drivers need it: RAY_TPU_AUTH_TOKEN or "
              f"init(auth_token=...))")
    print(f'connect with ray_tpu.init(address="{addr}")')


def _cmd_supervise(args):
    import logging
    logging.basicConfig(
        level="INFO",
        format="[supervisor %(asctime)s] %(levelname)s %(message)s")
    token = _read_token(args.token_file)
    from ray_tpu._private.node import NodeSupervisor
    NodeSupervisor(args.run_dir, head=args.head,
                   state_addr=args.address or "",
                   num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                   resources=json.loads(args.resources),
                   tp_cpu_devices=args.tp_cpu_devices,
                   heartbeat_timeout_ms=args.heartbeat_timeout_ms,
                   auth_token=token).run()


def _cmd_stop(args):
    if stop(args.run_dir):
        print("stopped")
    else:
        print("no running node found", file=sys.stderr)
        sys.exit(1)


def _cmd_status(args):
    info = status(address=args.address or None, run_dir=args.run_dir)
    print(f"cluster address: {info['address']}")
    alive = [n for n in info["nodes"] if n["alive"]]
    print(f"nodes: {len(alive)} alive / {len(info['nodes'])} total")
    for n in info["nodes"]:
        role = "head" if n["is_head"] else "worker"
        state = "ALIVE" if n["alive"] else "DEAD"
        print(f"  {n['node_id']} {role:6s} {state:5s} {n['address']:21s} "
              f"avail={n['available']} total={n['total']}")


def _add_node_args(p):
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default="",
                   help="state-service address of an existing cluster")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--tp-cpu-devices", type=int, default=0)
    p.add_argument("--run-dir", default=DEFAULT_RUN_DIR)
    p.add_argument("--heartbeat-timeout-ms", type=float, default=5000)
    p.add_argument("--token-file", default="",
                   help="shared-secret file (head generates one by default)")
    p.add_argument("--no-auth", action="store_true",
                   help="run an OPEN cluster (any socket can submit work)")


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu cluster")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("start")
    _add_node_args(sp)
    sp.add_argument("--block", action="store_true",
                    help="supervise in the foreground")
    sp.set_defaults(fn=_cmd_start)
    vp = sub.add_parser("supervise")
    _add_node_args(vp)
    vp.set_defaults(fn=_cmd_supervise)
    tp = sub.add_parser("stop")
    tp.add_argument("--run-dir", default=DEFAULT_RUN_DIR)
    tp.set_defaults(fn=_cmd_stop)
    up = sub.add_parser("status")
    up.add_argument("--run-dir", default=DEFAULT_RUN_DIR)
    up.add_argument("--address", default="")
    up.set_defaults(fn=_cmd_status)
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
