"""The ``ray-tpu`` command line interface.

Parity with the reference's click CLI (``python/ray/scripts/scripts.py``:
``status`` :1461, ``memory`` :1820, ``timeline`` :1755, ``list`` via the
state CLI ``experimental/state/state_cli.py``). Attaches to a running
driver's state server through the session port file; ``start`` boots a
standalone head runtime that idles serving state (for smoke tests — the
normal entry point is ``ray_tpu.init`` inside the driver).

Usage:
  python -m ray_tpu.scripts.cli status
  python -m ray_tpu.scripts.cli list tasks|actors|nodes|objects|pgs
  python -m ray_tpu.scripts.cli summary
  python -m ray_tpu.scripts.cli memory
  python -m ray_tpu.scripts.cli timeline -o /tmp/trace.json
  python -m ray_tpu.scripts.cli events
  python -m ray_tpu.scripts.cli doctor --json
  python -m ray_tpu.scripts.cli top --address HOST:PORT [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _fetch(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read().decode())


def _port(args) -> int:
    if args.port:
        return args.port
    from ray_tpu._private.state_server import discover_port
    port = discover_port()
    if port is None:
        print("No running ray_tpu driver found (no state server port "
              "file). Start one with ray_tpu.init().", file=sys.stderr)
        sys.exit(1)
    return port


def cmd_status(args):
    status = _fetch(_port(args), "/api/status")
    if not status.get("initialized"):
        print("ray_tpu: not initialized")
        return
    nodes = status["nodes"]
    alive = sum(1 for n in nodes if n["state"] == "ALIVE")
    print(f"Nodes: {alive} alive / {len(nodes)} total")
    print("Resources:")
    avail = status["available_resources"]
    for k, v in sorted(status["cluster_resources"].items()):
        print(f"  {avail.get(k, 0.0):.1f}/{v:.1f} {k}")
    ts = status["task_summary"]
    print(f"Tasks: {ts['total']} total {ts['by_state']}")
    asum = status["actor_summary"]
    print(f"Actors: {asum['total']} total {asum['by_state']}")


def cmd_list(args):
    rows = _fetch(_port(args), f"/api/{args.what}")
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args):
    status = _fetch(_port(args), "/api/status")
    print(json.dumps({"tasks": status.get("task_summary"),
                      "actors": status.get("actor_summary")}, indent=2))


def cmd_memory(args):
    objects = _fetch(_port(args), "/api/objects")
    print(f"{len(objects)} objects tracked")
    for o in objects[:args.limit]:
        print(f"  {o['object_id'][:16]} node={o['node_id'][:8]} "
              f"refs={o.get('ref_count')} in_store={o.get('in_store')}")


def cmd_timeline(args):
    trace = _fetch(_port(args), "/api/timeline")
    out = args.output or "ray-tpu-timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"Wrote {len(trace)} events to {out} "
          f"(open in chrome://tracing or Perfetto)")


def cmd_events(args):
    for ev in _fetch(_port(args), "/api/events")[-args.limit:]:
        print(json.dumps(ev, default=str))


def cmd_doctor(args):
    from ray_tpu.doctor import main as doctor_main
    argv = []
    if args.flight_dir:
        argv += ["--flight-dir", args.flight_dir]
    if args.address:
        argv += ["--address", args.address]
    if args.json:
        argv += ["--json"]
    if args.no_seal:
        argv += ["--no-seal"]
    if args.output:
        argv += ["--out", args.output]
    if args.perf_baseline:
        argv += ["--perf-baseline", args.perf_baseline]
    if args.goodput_baseline:
        argv += ["--goodput-baseline", args.goodput_baseline]
    if args.comms_baseline:
        argv += ["--comms-baseline", args.comms_baseline]
    sys.exit(doctor_main(argv))


def cmd_drain(args):
    from ray_tpu._private.state_client import StateClient
    client = StateClient(args.address)
    try:
        client.drain_node(bytes.fromhex(args.node_id),
                          reason=args.reason, deadline_s=args.deadline_s)
    finally:
        client.close()
    print(f"node {args.node_id[:16]} -> DRAINING "
          f"(reason={args.reason!r}, deadline_s={args.deadline_s or 'default'})")


def _top_rows(payload, subsystems=None):
    """Flatten an ``/api/perf`` payload into render rows:
    ``(node, name, summary, straggler)``.  A node is flagged a straggler
    on a histogram when its p95 is >= 3x the cluster median of the other
    nodes' p95 for that histogram (the doctor's outlier rule), with the
    same guards: at least 3 samples on the node and at least 2 reporting
    nodes."""
    import statistics
    nodes = payload.get("nodes", {})
    rows = []
    for name in sorted({n for per in nodes.values() for n in per}):
        subsystem = name.split(".", 1)[0]
        if subsystems and subsystem not in subsystems:
            continue
        p95s = [per[name]["p95_ms"] for per in nodes.values()
                if name in per]
        median = statistics.median(p95s) if p95s else 0.0
        for node in sorted(nodes):
            summ = nodes[node].get(name)
            if summ is None:
                # Partial federation: this node never recorded the
                # family (fresh node, subsystem not exercised there).
                # Emit a placeholder row — rendered as "—" — instead of
                # silently omitting the node from a filtered view.
                rows.append((node, name, None, False))
                continue
            straggler = (len(p95s) >= 2 and summ["count"] >= 3
                         and median > 0
                         and summ["p95_ms"] >= 3.0 * median)
            rows.append((node, name, summ, straggler))
    return rows


def _render_top(payload, subsystems=None) -> str:
    lines = ["%-14s %-22s %9s %9s %9s %9s %9s" % (
        "NODE", "HISTOGRAM", "COUNT", "MEAN_MS", "P50_MS", "P95_MS",
        "P99_MS")]
    for node, name, s, straggler in _top_rows(payload, subsystems):
        if s is None:  # family absent on this node: placeholder row
            lines.append("%-14s %-22s %9s %9s %9s %9s %9s" % (
                node, name, "—", "—", "—", "—", "—"))
            continue
        lines.append("%-14s %-22s %9d %9.2f %9.2f %9.2f %9.2f%s" % (
            node, name, int(s["count"]), s["mean_ms"], s["p50_ms"],
            s["p95_ms"], s["p99_ms"],
            "  <-- STRAGGLER (>=3x cluster median p95)"
            if straggler else ""))
    missing = payload.get("missing_hosts") or []
    if missing:
        lines.append(f"({len(missing)} unreachable host(s) omitted)")
    return "\n".join(lines)


def _render_goodput(payload) -> str:
    """Render an ``/api/goodput`` payload: per-job cluster totals first
    (the SLO view), then the per-node ledgers (the skew-triage view)."""
    cats = payload.get("categories") or []
    short = [c[:8] for c in cats]
    lines = ["%-14s %-10s %8s %8s " % ("NODE", "JOB", "WALL_S", "GOODPUT%")
             + " ".join("%8s" % s for s in short)]

    def fmt(label, job, rec):
        c = rec.get("cats") or {}
        return ("%-14s %-10s %8.1f %7.1f%% " % (
            label, job[:10], float(rec.get("wall_s", 0.0)),
            float(rec.get("goodput_pct", 0.0)))
            + " ".join("%8.2f" % float(c.get(k, 0.0)) for k in cats))

    for job, rec in sorted((payload.get("jobs") or {}).items()):
        lines.append(fmt("CLUSTER", job, rec))
    for node, jobs in sorted((payload.get("nodes") or {}).items()):
        for job, rec in sorted(jobs.items()):
            lines.append(fmt(node, job, rec))
    if len(lines) == 1:
        lines.append("(no goodput ledgers reported yet)")
    missing = payload.get("missing_hosts") or []
    if missing:
        lines.append(f"({len(missing)} unreachable host(s) omitted)")
    return "\n".join(lines)


def _render_comms(payload) -> str:
    """Render an ``/api/comms`` payload: the per-group op ledger (count,
    bytes, algbw/busbw over *wire* bytes, and the wire/logical
    compression ratio — 1.00 for uncompressed groups, ~0.27 for q8),
    the per-rank arrival-skew table with laggards marked, then the peer
    link matrix with outliers marked."""
    from ray_tpu.observability import comms as comms_mod
    lines = ["%-14s %-14s %7s %10s %10s %10s %6s" % (
        "GROUP", "OP", "COUNT", "MB", "ALGBW_GB/S", "BUSBW_GB/S", "RATIO")]
    groups = payload.get("groups") or {}
    for gname, rec in sorted(groups.items()):
        for op, o in sorted((rec.get("ops") or {}).items()):
            nbytes = float(o.get("bytes", 0))
            wire = float(o.get("wire_bytes", nbytes) or nbytes)
            ratio = o.get("compression_ratio")
            if ratio is None:
                ratio = (wire / nbytes) if nbytes else 1.0
            lines.append("%-14s %-14s %7d %10.1f %10.2f %10.2f %6.2f" % (
                gname, op, int(o.get("count", 0)), nbytes / 1e6,
                float(o.get("algbw_gbps", 0.0)),
                float(o.get("busbw_gbps", 0.0)), float(ratio)))
        if rec.get("mismatches"):
            lines.append(f"  {gname}: {rec['mismatches']} fingerprint "
                         "mismatch(es) — divergent collective submissions")
    if len(lines) == 1:
        lines.append("(no collective ops recorded yet)")
    skew = comms_mod.skew_report(groups, bounds=payload.get("bounds"))
    flagged = {(f["group"], f["rank"])
               for f in payload.get("skew_flags") or []}
    if skew:
        lines.append("")
        lines.append("%-14s %-6s %9s %9s %9s" % (
            "GROUP", "RANK", "ARRIVALS", "SKEW_P50", "SKEW_P95"))
        for gname, ranks in sorted(skew.items()):
            for rank, s in sorted(ranks.items(), key=lambda kv: kv[0]):
                lines.append("%-14s %-6s %9d %8.2fms %8.2fms%s" % (
                    gname, rank, int(s["count"]), s["p50_ms"], s["p95_ms"],
                    "  <-- LAGGARD (>=3x peer median p95)"
                    if (gname, rank) in flagged else ""))
    links = payload.get("links") or {}
    if links:
        flagged_links = {f["link"] for f in payload.get("link_flags") or []}
        lines.append("")
        lines.append("%-22s %-14s %8s %8s %8s %9s" % (
            "PEER", "CONSUMER", "GB/S", "CHUNKS", "RETRIES", "FAILOVERS"))
        for key, rec in sorted(links.items()):
            peer, _, consumer = key.partition("|")
            lines.append("%-22s %-14s %8.2f %8d %8d %9d%s" % (
                peer, consumer, float(rec.get("gbps", 0.0)),
                int(rec.get("chunks", 0)), int(rec.get("retries", 0)),
                int(rec.get("failovers", 0)),
                "  <-- DEGRADED" if key in flagged_links else ""))
    missing = payload.get("missing_hosts") or []
    if missing:
        lines.append(f"({len(missing)} unreachable host(s) omitted)")
    return "\n".join(lines)


def cmd_top(args):
    """Live per-node/per-subsystem latency table off the perf plane
    (``--goodput``: the per-job wall-clock attribution ledger;
    ``--comms``: the collective telemetry + link matrix instead)."""
    import time
    from ray_tpu._private.config import _config
    from ray_tpu.dashboard.head import DashboardHead
    subsystems = set(args.subsystem) if args.subsystem else None
    head = DashboardHead(args.address)
    try:
        if args.comms:
            if args.json:
                print(json.dumps(head._comms(), indent=2))
                return
            interval = args.interval or \
                float(_config.get("perf_top_interval_s"))
            while True:
                payload = head._comms()
                print("\x1b[2J\x1b[H", end="")
                print(f"ray-tpu top --comms — cluster {args.address} "
                      f"(refresh {interval:.1f}s, Ctrl-C to quit)")
                print(_render_comms(payload))
                time.sleep(interval)
        if args.goodput:
            if args.json:
                print(json.dumps(head._goodput(), indent=2))
                return
            interval = args.interval or \
                float(_config.get("perf_top_interval_s"))
            while True:
                payload = head._goodput()
                print("\x1b[2J\x1b[H", end="")
                print(f"ray-tpu top --goodput — cluster {args.address} "
                      f"(refresh {interval:.1f}s, Ctrl-C to quit)")
                print(_render_goodput(payload))
                time.sleep(interval)
        if args.json:
            payload = head._perf()
            payload["stragglers"] = [
                {"node": node, "name": name}
                for node, name, _s, flag in _top_rows(payload, subsystems)
                if flag]
            if subsystems:
                for per in list(payload["nodes"].values()) + \
                        [payload["cluster"]]:
                    for name in [n for n in per
                                 if n.split(".", 1)[0] not in subsystems]:
                        del per[name]
            print(json.dumps(payload, indent=2))
            return
        interval = args.interval or float(_config.get("perf_top_interval_s"))
        while True:
            payload = head._perf()
            print("\x1b[2J\x1b[H", end="")
            print(f"ray-tpu top — cluster {args.address} "
                  f"(refresh {interval:.1f}s, Ctrl-C to quit)")
            print(_render_top(payload, subsystems))
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    finally:
        head.stop()


def cmd_dashboard(args):
    import time
    from ray_tpu.dashboard import start_dashboard
    head = start_dashboard(args.address, port=args.dashboard_port,
                           host=args.host)
    print(f"dashboard at http://{args.host}:{head.port}/ "
          f"(cluster {args.address}); Ctrl-C to stop")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        head.stop()


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu")
    p.add_argument("--port", type=int, default=None,
                   help="state server port (default: session file)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status").set_defaults(fn=cmd_status)
    lp = sub.add_parser("list")
    lp.add_argument("what",
                    choices=["tasks", "actors", "nodes", "objects", "pgs"])
    lp.set_defaults(fn=cmd_list)
    sub.add_parser("summary").set_defaults(fn=cmd_summary)
    mp = sub.add_parser("memory")
    mp.add_argument("--limit", type=int, default=50)
    mp.set_defaults(fn=cmd_memory)
    tp = sub.add_parser("timeline")
    tp.add_argument("-o", "--output", default=None)
    tp.set_defaults(fn=cmd_timeline)
    ep = sub.add_parser("events")
    ep.add_argument("--limit", type=int, default=100)
    ep.set_defaults(fn=cmd_events)
    hp = sub.add_parser("doctor",
                        help="crash forensics + cluster health diagnosis")
    hp.add_argument("--flight-dir", default=None)
    hp.add_argument("--address", default=None,
                    help="state service host:port for live collection")
    hp.add_argument("--json", action="store_true")
    hp.add_argument("--no-seal", action="store_true")
    hp.add_argument("-o", "--output", default=None)
    hp.add_argument("--perf-baseline", default=None,
                    help="JSON quantile budgets; drift counts as issues")
    hp.add_argument("--goodput-baseline", default=None,
                    help="JSON goodput budgets (per-job goodput_pct "
                         "floors); drift counts as issues")
    hp.add_argument("--comms-baseline", default=None,
                    help="JSON comms budgets (per-group <op>_gbps floors, "
                         "skew_p95_ms/mismatches ceilings); drift counts "
                         "as issues")
    hp.set_defaults(fn=cmd_doctor)
    gp = sub.add_parser("drain",
                        help="gracefully drain a node (workload migration)")
    gp.add_argument("node_id", help="node id (hex, as shown by `list nodes`)")
    gp.add_argument("--address", required=True,
                    help="host:port of the cluster state service")
    gp.add_argument("--reason", default="operator")
    gp.add_argument("--deadline-s", type=float, default=0.0,
                    help="drain budget in seconds (0 = drain_deadline_s)")
    gp.set_defaults(fn=cmd_drain)
    op = sub.add_parser(
        "top", help="live per-node latency quantiles from the perf plane")
    op.add_argument("--address", required=True,
                    help="host:port of the cluster state service")
    op.add_argument("--json", action="store_true",
                    help="print one /api/perf snapshot as JSON and exit")
    op.add_argument("--interval", type=float, default=0.0,
                    help="refresh seconds (0 = perf_top_interval_s config)")
    op.add_argument("--subsystem", action="append", default=None,
                    help="filter to a subsystem prefix (rpc, task, fetch, "
                         "ckpt, serve, train, ...); repeatable")
    op.add_argument("--goodput", action="store_true",
                    help="show the per-job goodput ledger (/api/goodput) "
                         "instead of latency quantiles")
    op.add_argument("--comms", action="store_true",
                    help="show collective telemetry, rank arrival skew "
                         "and the peer link matrix (/api/comms) instead "
                         "of latency quantiles")
    op.set_defaults(fn=cmd_top)
    dp = sub.add_parser("dashboard",
                        help="serve the cluster dashboard UI")
    dp.add_argument("--address", required=True,
                    help="host:port of the cluster state service")
    dp.add_argument("--dashboard-port", type=int, default=8265)
    dp.add_argument("--host", default="127.0.0.1")
    dp.set_defaults(fn=cmd_dashboard)
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
