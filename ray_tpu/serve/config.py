"""Serve configuration dataclasses.

Parity with the reference's ``python/ray/serve/config.py`` (DeploymentConfig,
AutoscalingConfig) — the knobs a deployment exposes: replica counts,
per-replica concurrency, autoscaling bounds, rolling-update rates, and
user_config pushed to live replicas.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth-driven autoscaling (reference:
    ``serve/_private/autoscaling_policy.py``)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 30.0
    smoothing_factor: float = 1.0

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        if current == 0:
            return max(1, self.min_replicas)
        per_replica = total_ongoing / current
        error = per_replica / max(
            self.target_num_ongoing_requests_per_replica, 1e-9)
        desired = current * (1.0 + self.smoothing_factor * (error - 1.0))
        import math
        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 10.0
    graceful_shutdown_timeout_s: float = 20.0
    # Model weights source: a ray_tpu.checkpoint.CheckpointRef (or its
    # {"root", "manifest_name"} dict form after config serialization).
    # Replicas cold-start by loading the manifest on the replica actor —
    # weights come from the content-addressed store, never through the
    # controller. Changing it is a version change (rolling update).
    checkpoint: Optional[Any] = None

    def version_hash(self, func_or_class, init_args, init_kwargs) -> str:
        """Code/config version: changing it triggers a rolling update;
        changing only user_config reconfigures replicas in place
        (reference: deployment_state version semantics).  The hash covers
        the callable's source (so edited code redeploys) plus init args,
        actor options, and the checkpoint manifest pin."""
        import hashlib
        import inspect
        import pickle
        try:
            code = inspect.getsource(func_or_class)
        except Exception:  # raylint: allow(swallow) source unavailable: fall back to qualname
            code = getattr(func_or_class, "__qualname__",
                           repr(func_or_class))
        ckpt = self.checkpoint
        if dataclasses.is_dataclass(ckpt):
            ckpt = dataclasses.asdict(ckpt)
        try:
            payload = pickle.dumps(
                (code, init_args, init_kwargs, self.ray_actor_options,
                 ckpt))
        except Exception:  # raylint: allow(swallow) unpicklable config: fall back to repr
            payload = repr((code, init_args, init_kwargs, ckpt)).encode()
        return hashlib.sha1(payload).hexdigest()[:12]
