"""Serve configuration dataclasses.

Parity with the reference's ``python/ray/serve/config.py`` (DeploymentConfig,
AutoscalingConfig) — the knobs a deployment exposes: replica counts,
per-replica concurrency, autoscaling bounds, rolling-update rates, and
user_config pushed to live replicas.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth-driven autoscaling (reference:
    ``serve/_private/autoscaling_policy.py``), plus the latency-SLO mode:
    with ``target_latency_ms > 0`` the controller scales on the
    EWMA-smoothed federated ``serve.queue_wait`` + execute p95 from the
    perf plane instead of instantaneous queue depth."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 30.0
    smoothing_factor: float = 1.0
    # Latency SLO (ms) the deployment should hold at p95; 0 keeps the
    # queue-depth policy above.
    target_latency_ms: float = 0.0

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        if current == 0:
            return max(1, self.min_replicas)
        per_replica = total_ongoing / current
        error = per_replica / max(
            self.target_num_ongoing_requests_per_replica, 1e-9)
        desired = current * (1.0 + self.smoothing_factor * (error - 1.0))
        import math
        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))

    def desired_replicas_for_latency(self, p95_ms: float,
                                     current: int) -> int:
        """SLO mode: same multiplicative controller as the queue policy,
        but the error signal is observed-p95 / SLO.  p95 == 0 (no recent
        traffic) drives toward ``min_replicas``."""
        if current == 0:
            return max(1, self.min_replicas)
        error = p95_ms / max(self.target_latency_ms, 1e-9)
        desired = current * (1.0 + self.smoothing_factor * (error - 1.0))
        import math
        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 10.0
    graceful_shutdown_timeout_s: float = 20.0
    # Model weights source: a ray_tpu.checkpoint.CheckpointRef (or its
    # {"root", "manifest_name"} dict form after config serialization).
    # Replicas cold-start by loading the manifest on the replica actor —
    # weights come from the content-addressed store, never through the
    # controller. Changing it is a version change (rolling update).
    checkpoint: Optional[Any] = None
    # Replica-side continuous batching: > 1 turns the replica into an
    # adaptive micro-batcher — __call__ (and function deployments) must
    # then accept a LIST of requests and return a list of equal length.
    max_batch_size: int = 1
    # Max linger the oldest queued request waits for its batch to fill.
    batch_wait_timeout_s: float = 0.005
    # Pad-to-bucket shapes: batches are padded (repeating the last item)
    # up to the next bucket so a jitted forward sees only these static
    # batch sizes and never recompiles per batch size.
    pad_batch_to: Optional[Tuple[int, ...]] = None
    # Per-request latency budget (ms) the batcher sizes batches against
    # and the router sheds over; 0 falls back to the global
    # serve_target_latency_ms knob.
    target_latency_ms: float = 0.0

    def effective_target_latency_ms(self) -> float:
        if self.target_latency_ms > 0:
            return float(self.target_latency_ms)
        from ray_tpu._private.config import _config
        return float(_config.get("serve_target_latency_ms"))

    def version_hash(self, func_or_class, init_args, init_kwargs) -> str:
        """Code/config version: changing it triggers a rolling update;
        changing only user_config reconfigures replicas in place
        (reference: deployment_state version semantics).  The hash covers
        the callable's source (so edited code redeploys) plus init args,
        actor options, and the checkpoint manifest pin."""
        import hashlib
        import inspect
        import pickle
        try:
            code = inspect.getsource(func_or_class)
        except Exception:  # raylint: allow(swallow) source unavailable: fall back to qualname
            code = getattr(func_or_class, "__qualname__",
                           repr(func_or_class))
        ckpt = self.checkpoint
        if dataclasses.is_dataclass(ckpt):
            ckpt = dataclasses.asdict(ckpt)
        try:
            payload = pickle.dumps(
                (code, init_args, init_kwargs, self.ray_actor_options,
                 ckpt))
        except Exception:  # raylint: allow(swallow) unpicklable config: fall back to repr
            payload = repr((code, init_args, init_kwargs, ckpt)).encode()
        return hashlib.sha1(payload).hexdigest()[:12]
