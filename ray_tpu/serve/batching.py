"""Adaptive request batching for deployments.

Parity with ``python/ray/serve/batching.py`` (``@serve.batch``): concurrent
calls to the wrapped method are grouped into one invocation receiving a
list of inputs and returning a list of outputs; each caller gets its own
element back.  A batch flushes when it reaches ``max_batch_size`` or when
the oldest request has waited ``batch_wait_timeout_s``.

TPU-first addition: ``pad_batch_to`` — a sorted tuple of bucket sizes.
When set, the invoked batch list is padded (by repeating the last element)
up to the next bucket so the wrapped ``jax.jit`` function sees only a few
static batch shapes and never recompiles per batch size; padded outputs
are dropped before delivery.
"""

from __future__ import annotations

import functools
import itertools
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ray_tpu.exceptions import BatchExecutionError

_request_counter = itertools.count()


def next_request_id() -> int:
    """Process-unique id stamped on each batched request so batch-level
    failures (``BatchExecutionError``) can name their members.  Shared
    with the replica-side micro-batcher."""
    return next(_request_counter)


def next_bucket(n: int, buckets: Optional[Tuple[int, ...]]) -> int:
    """Smallest bucket >= n (the largest bucket when n overflows them);
    n itself when no buckets are configured."""
    if not buckets:
        return n
    return next((b for b in buckets if b >= n), buckets[-1])


def pad_items(items: List[Any], buckets: Optional[Tuple[int, ...]]
              ) -> List[Any]:
    """Pad ``items`` (repeating the last element) up to the next bucket so
    a jitted forward only ever sees ``len(buckets)`` static batch shapes.
    Shared by the ``@serve.batch`` decorator and the replica-side
    micro-batcher — one owner of the pad-to-bucket rule."""
    target = next_bucket(len(items), buckets)
    if target > len(items):
        return items + [items[-1]] * (target - len(items))
    return items


class _Slot:
    __slots__ = ("item", "event", "value", "error", "request_id")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.request_id = next_request_id()


class _BatchQueue:
    """A dedicated daemon flusher thread drains the queue, so a caller's
    latency is bounded by its own batch — under sustained traffic no caller
    is ever conscripted into flushing others' batches."""

    def __init__(self, fn: Callable[[Any, List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float,
                 pad_batch_to: Optional[Tuple[int, ...]]):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._buckets = tuple(sorted(pad_batch_to)) if pad_batch_to else None
        self._lock = threading.Lock()
        self._pending: List[_Slot] = []  # raylint: guarded-by(self._lock)
        self._instance = None
        self._wakeup = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def submit(self, instance, item) -> Any:
        slot = _Slot(item)
        with self._lock:
            self._instance = instance  # raylint: guarded-by(self._lock)
            self._pending.append(slot)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name=f"serve-batch-{self._fn.__name__}")
                self._thread.start()
        self._wakeup.set()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.value

    def _flush_loop(self) -> None:
        import time
        while True:
            self._wakeup.wait()
            # Batch window: from the first pending request, wait until the
            # batch fills or batch_wait_timeout_s elapses.
            deadline = time.monotonic() + self._timeout
            while True:
                with self._lock:
                    n = len(self._pending)
                if n >= self._max or time.monotonic() >= deadline:
                    break
                time.sleep(min(0.001, max(self._timeout / 10, 1e-4)))
            with self._lock:
                batch, self._pending = (self._pending[:self._max],
                                        self._pending[self._max:])
                instance = self._instance
                if not self._pending:
                    self._wakeup.clear()
            if batch:
                self._execute(instance, batch)

    def _call(self, instance, items: List[Any]) -> List[Any]:
        n = len(items)
        items = pad_items(items, self._buckets)
        if instance is not None:
            results = self._fn(instance, items)
        else:
            results = self._fn(items)
        results = list(results)[:n]
        if len(results) != n:
            raise ValueError(
                f"batched function returned {len(results)} results "
                f"for {n} inputs")
        return results

    def _execute(self, instance, batch: List[_Slot]) -> None:
        try:
            results = self._call(instance, [s.item for s in batch])
            for slot, value in zip(batch, results):
                slot.value = value
                slot.event.set()
            return
        except BaseException as e:
            error = e
        # Batch-level failure.  A singleton batch gets its own error raw —
        # there is no ambiguity about whose request poisoned it.  For
        # multi-item batches, optionally re-run each member alone once so
        # poisoned requests fail alone and innocent batchmates still get
        # answers; otherwise stamp a batch-level tag carrying the batch
        # size and request ids so callers can tell "my request was bad"
        # from "I was collateral".
        if len(batch) == 1:
            batch[0].error = error
            batch[0].event.set()
            return
        from ray_tpu._private.config import _config
        if _config.get("serve_batch_retry_singletons"):
            for slot in batch:
                try:
                    slot.value = self._call(instance, [slot.item])[0]
                except BaseException as single_err:
                    slot.error = single_err
                slot.event.set()
            return
        tagged = BatchExecutionError(
            self._fn.__name__, len(batch),
            [s.request_id for s in batch], error)
        for slot in batch:
            slot.error = tagged
            slot.event.set()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01,
          pad_batch_to: Optional[Sequence[int]] = None):
    """Decorator converting ``f(self, item)`` call sites into batched
    ``f(self, [items])`` execution.  The wrapped function must accept a
    list and return a list of equal length."""

    def wrap(fn: Callable):
        queue_attr = f"__batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if kwargs:
                raise ValueError("@serve.batch methods take one positional "
                                 "request argument")
            if len(args) == 2:  # bound method: (self, item)
                instance, item = args
                holder = instance
            elif len(args) == 1:  # plain function: (item,)
                instance, item = None, args[0]
                holder = wrapper
            else:
                raise ValueError("@serve.batch methods take exactly one "
                                 "request argument")
            queue = getattr(holder, queue_attr, None)
            if queue is None:
                queue = _BatchQueue(
                    fn, max_batch_size, batch_wait_timeout_s,
                    tuple(pad_batch_to) if pad_batch_to else None)
                setattr(holder, queue_attr, queue)
            return queue.submit(instance, item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
