"""HTTP ingress for Serve.

Parity with ``python/ray/serve/_private/http_proxy.py``: an actor running
an HTTP server that maps route prefixes to deployments (table pushed from
the controller via long-poll) and forwards request bodies through a
``DeploymentHandle``. The reference uses uvicorn/ASGI; here the server
is the stdlib threading HTTP server hardened with the proxy-level
behaviors the ASGI stack provides:

- **Ingress concurrency limiting**: at most ``max_concurrent_requests``
  requests execute at once; excess requests are rejected immediately
  with 503 + Retry-After (the proxy's half of the reference's
  ``max_ongoing_requests`` backpressure) instead of stacking threads.
- **Streaming responses**: list/tuple results stream as
  chunked-transfer pieces when the client asks
  (``X-Serve-Stream: 1``) — element-wise flush, so large outputs don't
  buffer into one JSON blob. (Replica execution itself completes
  before streaming starts: the task protocol replies once; this is
  response streaming, not incremental generation.)
- **Utility endpoints**: ``/-/healthz`` and ``/-/routes`` (same paths
  as the reference proxy's health/routes endpoints).
- **Draining**: during shutdown new requests get 503 while in-flight
  ones finish.

Request convention: POST body is JSON (or raw bytes if not JSON) passed
as the single argument; the JSON-serialized return value is the
response.
"""

from __future__ import annotations
import logging

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ray_tpu import observability
from ray_tpu._private.config import _config
from ray_tpu.exceptions import ServeOverloadedError
from ray_tpu.observability import perf
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve.controller import ROUTE_TABLE_KEY
from ray_tpu.serve.handle import DeploymentHandle

logger = logging.getLogger("ray_tpu")


class HTTPProxy:
    def __init__(self, controller_handle, host: str = "127.0.0.1",
                 port: int = 0, max_concurrent_requests: int = 200,
                 request_timeout_s: float = 60.0):
        self._controller = controller_handle
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, DeploymentHandle] = {}
        self._lock = threading.Lock()
        self._inflight = threading.Semaphore(max_concurrent_requests)
        self._draining = False
        self._timeout_s = request_timeout_s
        import ray_tpu
        self._routes = ray_tpu.get(
            controller_handle.get_route_table.remote())
        self._poller = LongPollClient(
            controller_handle, {ROUTE_TABLE_KEY: self._update_routes})

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # chunked streaming needs 1.1

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, payload: dict,
                      retry_after_s: float = 1.0):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if code == 503:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(retry_after_s)))))
                self.end_headers()
                self.wfile.write(body)

            def _stream(self, items):
                self._headers_sent = True
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for item in items:
                    piece = (json.dumps(item) + "\n").encode()
                    self.wfile.write(
                        f"{len(piece):x}\r\n".encode() + piece + b"\r\n")
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")

            def _dispatch(self, body: Optional[bytes]):
                t_arrival = time.monotonic() if perf.ENABLED else 0.0
                path = self.path.split("?")[0].rstrip("/") or "/"
                if path == "/-/healthz":
                    self._json(503 if proxy._draining else 200,
                               {"status": "draining"
                                if proxy._draining else "ok"})
                    return
                if path == "/-/routes":
                    with proxy._lock:
                        self._json(200, dict(proxy._routes))
                    return
                if proxy._draining:
                    self._json(503, {"error": "proxy draining"})
                    return
                if not proxy._inflight.acquire(blocking=False):
                    # Backpressure at ingress: reject NOW rather than
                    # stacking unbounded handler threads on a saturated
                    # cluster (max_ongoing_requests role).
                    self._json(503, {"error": "too many in-flight "
                                              "requests"})
                    return
                try:
                    # Serve request = trace entry point: the span below
                    # mints a trace_id (no enclosing context in a proxy
                    # thread), and the replica task submitted by
                    # handle.remote() inherits it via TaskSpec.
                    with observability.span("serve.request", cat="serve",
                                            route=path):
                        name = proxy._match(path)
                        if name is None:
                            self._json(404, {"error": "no route"})
                            return
                        arg = None
                        if body:
                            try:
                                arg = json.loads(body)
                            except json.JSONDecodeError:
                                arg = body
                        if isinstance(arg, (bytes, bytearray)):
                            arg = proxy._maybe_put_ingress(arg)
                        handle = proxy._get_handle(name)
                        # Perf breakdown: queue_wait (semaphore + routing
                        # + body handling, the pre-dispatch share) vs
                        # execute (replica round-trip) vs serialize
                        # (response encode + write).
                        t_exec = time.monotonic() if t_arrival else 0.0
                        if t_arrival:
                            perf.observe("serve.queue_wait",
                                         (t_exec - t_arrival) * 1e3)
                        result = handle.remote(arg).result(
                            timeout=proxy._timeout_s)
                        t_ser = time.monotonic() if t_arrival else 0.0
                        if t_arrival:
                            perf.observe("serve.execute",
                                         (t_ser - t_exec) * 1e3)
                        try:
                            if (isinstance(result, (list, tuple))
                                    and self.headers.get("X-Serve-Stream")):
                                self._stream(result)
                                return
                            self._send_value(result)
                        finally:
                            if t_arrival:
                                now = time.monotonic()
                                perf.observe("serve.serialize",
                                             (now - t_ser) * 1e3)
                except Exception as e:  # noqa: BLE001 - surface to caller
                    if getattr(self, "_headers_sent", False):
                        # Mid-stream failure: a second status line would
                        # corrupt the half-sent chunked body AND poison
                        # the keep-alive connection — just sever it.
                        self.close_connection = True
                        try:
                            self.wfile.flush()
                        except OSError:
                            pass
                    elif isinstance(e, ServeOverloadedError):
                        # Serve shed the request (router: every replica
                        # over budget; replica: queue-deadline ageout).
                        self._json(503, {"error": str(e)},
                                   retry_after_s=e.retry_after_s)
                    elif isinstance(e, TimeoutError):
                        # Includes the router's bounded pick (no replica
                        # freed a slot within serve_queue_deadline_ms):
                        # overload presents as a fast 503, never a hang.
                        self._json(503, {"error": str(e)})
                    else:
                        self._json(500, {"error": str(e)})
                finally:
                    proxy._inflight.release()
                    if t_arrival:
                        perf.observe("serve.request",
                                     (time.monotonic() - t_arrival) * 1e3)

            def _send_value(self, result):
                body = json.dumps(result).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self._dispatch(self.rfile.read(length) if length else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._thread.start()

    def _update_routes(self, table: Dict[str, str]) -> None:
        with self._lock:
            self._routes = dict(table)
            keep, dropped = {}, []
            for name, handle in self._handles.items():
                if name in table.values():
                    keep[name] = handle
                else:
                    dropped.append(handle)
            self._handles = keep
        # Shut down routers of dropped handles outside the lock so their
        # long-poll threads don't leak controller listener slots.
        for handle in dropped:
            try:
                handle.shutdown()
            except Exception as e:
                logger.debug("handle shutdown failed: %s", e)

    def _maybe_put_ingress(self, body):
        """Large raw (non-JSON) request bodies go into the object plane
        and ride to the replica as a ref: the bulk bytes then move over
        the shared striped transport pool (proactive push / striped
        fetch) instead of being pickled into the task args — the serve
        half of ROADMAP item 5's TCP-throughput chase.  The replica sees
        the original bytes (task args auto-resolve refs)."""
        threshold = int(_config.get("serve_ingress_put_threshold_bytes"))
        if threshold <= 0 or len(body) < threshold:
            return body
        import ray_tpu
        t0 = time.monotonic() if perf.ENABLED else 0.0
        try:
            ref = ray_tpu.put(bytes(body))
        except Exception as e:  # noqa: BLE001 — inline args still correct
            logger.debug("serve ingress put failed (%s); "
                         "falling back to inline body", e)
            return body
        if t0:
            perf.observe("serve.ingress_put",
                         (time.monotonic() - t0) * 1e3)
        return ref

    def _match(self, path: str) -> Optional[str]:
        with self._lock:
            # Longest-prefix match, '/' as catch-all.
            best = None
            for prefix, name in self._routes.items():
                p = prefix.rstrip("/") or "/"
                if path == p or path.startswith(p + "/") or p == "/":
                    if best is None or len(p) > len(best[0]):
                        best = (p, name)
            return best[1] if best else None

    def _get_handle(self, name: str) -> DeploymentHandle:
        with self._lock:
            if name not in self._handles:
                self._handles[name] = DeploymentHandle(name, self._controller)
            return self._handles[name]

    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._draining = True
        self._poller.stop()
        self._server.shutdown()
        self._server.server_close()
