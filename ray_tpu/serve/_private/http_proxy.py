"""HTTP ingress for Serve.

Parity with ``python/ray/serve/_private/http_proxy.py``: an actor running
an HTTP server that maps route prefixes to deployments (table pushed from
the controller via long-poll) and forwards request bodies through a
``DeploymentHandle``.  The reference uses uvicorn/ASGI; here the server is
the stdlib threading HTTP server — ingress is control-path, the data path
(model execution) stays in replicas.

Request convention: POST body is JSON (or raw bytes if not JSON) passed as
the single argument; the JSON-serialized return value is the response.
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve.controller import ROUTE_TABLE_KEY
from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxy:
    def __init__(self, controller_handle, host: str = "127.0.0.1",
                 port: int = 0):
        self._controller = controller_handle
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, DeploymentHandle] = {}
        self._lock = threading.Lock()
        import ray_tpu
        self._routes = ray_tpu.get(
            controller_handle.get_route_table.remote())
        self._poller = LongPollClient(
            controller_handle, {ROUTE_TABLE_KEY: self._update_routes})

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body: Optional[bytes]):
                path = self.path.split("?")[0].rstrip("/") or "/"
                name = proxy._match(path)
                if name is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no route"}')
                    return
                try:
                    arg = None
                    if body:
                        try:
                            arg = json.loads(body)
                        except json.JSONDecodeError:
                            arg = body
                    handle = proxy._get_handle(name)
                    result = handle.remote(arg).result(timeout=60)
                    payload = json.dumps(result).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(
                        json.dumps({"error": str(e)}).encode())

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self._dispatch(self.rfile.read(length) if length else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._thread.start()

    def _update_routes(self, table: Dict[str, str]) -> None:
        with self._lock:
            self._routes = dict(table)
            keep, dropped = {}, []
            for name, handle in self._handles.items():
                if name in table.values():
                    keep[name] = handle
                else:
                    dropped.append(handle)
            self._handles = keep
        # Shut down routers of dropped handles outside the lock so their
        # long-poll threads don't leak controller listener slots.
        for handle in dropped:
            try:
                handle.shutdown()
            except Exception:
                pass

    def _match(self, path: str) -> Optional[str]:
        with self._lock:
            # Longest-prefix match, '/' as catch-all.
            best = None
            for prefix, name in self._routes.items():
                p = prefix.rstrip("/") or "/"
                if path == p or path.startswith(p + "/") or p == "/":
                    if best is None or len(p) > len(best[0]):
                        best = (p, name)
            return best[1] if best else None

    def _get_handle(self, name: str) -> DeploymentHandle:
        with self._lock:
            if name not in self._handles:
                self._handles[name] = DeploymentHandle(name, self._controller)
            return self._handles[name]

    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._poller.stop()
        self._server.shutdown()
        self._server.server_close()
