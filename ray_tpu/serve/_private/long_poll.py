"""Long-poll pub/sub between the controller and routers/proxies.

Parity with ``python/ray/serve/_private/long_poll.py`` (``LongPollHost``
``:63``, ``LongPollClient`` ``:179``): listeners ask the host for "changes
since snapshot_id N" and block server-side until something changes, so
config propagation is push-shaped without a persistent connection per key.

The host lives inside the controller actor; its ``listen_for_change`` call
blocks on a condition variable (the controller runs with max_concurrency,
so blocked listeners don't stall control-loop method calls).
"""

from __future__ import annotations
import logging

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.backoff import BackoffPolicy


class LongPollHost:
    def __init__(self):
        self._snapshot_ids: Dict[str, int] = {}  # raylint: guarded-by(self._cond)
        self._objects: Dict[str, Any] = {}  # raylint: guarded-by(self._cond)
        self._cond = threading.Condition()

    def notify_changed(self, key: str, obj: Any) -> None:
        with self._cond:
            self._objects[key] = obj
            self._snapshot_ids[key] = self._snapshot_ids.get(key, 0) + 1
            self._cond.notify_all()

    def notify_if_changed(self, key: str, obj: Any) -> bool:
        """``notify_changed`` that dedups: skip the snapshot bump (and the
        listener wakeups) when ``obj`` equals the currently published
        value.  The control loop publishes per-replica latency stats every
        tick; without this every idle tick would fan a no-op update out to
        every router.  Returns True when a notification was published."""
        with self._cond:
            if key in self._objects and self._objects[key] == obj:
                return False
            self._objects[key] = obj
            self._snapshot_ids[key] = self._snapshot_ids.get(key, 0) + 1
            self._cond.notify_all()
            return True

    def listen_for_change(
            self, keys_to_snapshot_ids: Dict[str, int],
            timeout_s: float = 30.0) -> Dict[str, Tuple[int, Any]]:
        """Block until any watched key moves past the caller's snapshot id.

        Returns {key: (new_snapshot_id, object)} for changed keys only;
        empty dict on timeout.
        """
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                updates = {
                    key: (self._snapshot_ids[key], self._objects[key])
                    for key, since in keys_to_snapshot_ids.items()
                    if self._snapshot_ids.get(key, 0) > since
                }
                if updates:
                    return updates
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._cond.wait(remaining)


import weakref

logger = logging.getLogger("ray_tpu")

_live_clients: "weakref.WeakSet" = weakref.WeakSet()


def stop_all_clients(join_timeout_s: float = 3.0) -> None:
    """Stop every live long-poll loop in this process AND join the threads:
    serve shutdown calls this so no poller can slip one more .remote()
    past the runtime teardown and auto-reinitialize the worker."""
    clients = list(_live_clients)
    for client in clients:
        client.stop()
    deadline = time.monotonic() + join_timeout_s
    for client in clients:
        t = getattr(client, "_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class LongPollClient:
    """Background thread long-polling the controller for watched keys."""

    def __init__(self, controller_handle,
                 key_listeners: Dict[str, Callable[[Any], None]]):
        import ray_tpu
        self._ray = ray_tpu
        self._controller = controller_handle
        self._listeners = dict(key_listeners)
        self._snapshot_ids = {k: 0 for k in self._listeners}
        self._stopped = threading.Event()
        _live_clients.add(self)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-long-poll")
        self._thread.start()

    def _loop(self) -> None:
        poll_backoff = BackoffPolicy(base_s=0.2, max_s=5.0, deadline_s=0)
        errors = 0
        while not self._stopped.is_set():
            try:
                ref = self._controller.listen_for_change.remote(
                    dict(self._snapshot_ids))
                updates = self._ray.get(ref, timeout=60)
            except Exception as e:
                logger.debug("long poll failed; retrying: %s", e)
                if self._stopped.is_set():
                    return
                errors += 1
                self._stopped.wait(poll_backoff.delay_for(errors - 1))
                continue
            errors = 0
            for key, (snapshot_id, obj) in updates.items():
                self._snapshot_ids[key] = snapshot_id
                try:
                    self._listeners[key](obj)
                except Exception as e:
                    logger.warning("long-poll listener raised: %s", e)

    def stop(self) -> None:
        self._stopped.set()
