"""Replica actor: hosts one copy of a deployment's user callable.

Parity with ``python/ray/serve/_private/replica.py``: runs the user class
(or function), counts ongoing requests for autoscaling/backpressure,
supports ``reconfigure(user_config)`` in place, health checks, and
graceful drain before shutdown.

TPU note: a replica is where compiled inference lives — the user callable
typically closes over a ``jax.jit``'d function.  Replicas stay alive across
requests precisely so XLA compilation caches stay warm; a rolling update
replaces replicas one at a time so the app never serves with a cold cache
on every replica at once.

Continuous batching: with ``max_batch_size > 1`` the replica becomes an
adaptive micro-batcher.  Incoming ``__call__`` requests are admitted into
an in-replica queue (each caller's actor thread parks on its slot, so
``max_concurrent_queries`` still bounds admission); a dedicated flusher
thread coalesces queued requests into pad-to-bucket batches — reusing the
``pad_batch_to`` bucket rule from ``serve/batching.py`` so one jitted
forward sees only ``len(buckets)`` static shapes and never recompiles per
batch size — and invokes the user callable once per batch with a LIST of
requests.  Batch size adapts to observed queue depth, capped so the
EWMA-predicted batch time stays inside the replica's latency budget
(``target_latency_ms`` falling back to the ``serve_target_latency_ms``
knob).  Requests that age past ``serve_queue_deadline_ms`` in the queue
are shed with :class:`ServeOverloadedError` instead of executing — the
proxy maps that to 503 + Retry-After.  A failed batch isolates per item:
singleton batches get their own error raw; larger batches re-run members
alone once (``serve_batch_retry_singletons``) or receive a batch-level
:class:`BatchExecutionError` naming the batch size and request ids.

Every request — batched or direct — feeds two replica-local
:class:`~ray_tpu.observability.perf.PerfHistogram` instances
(``queue_wait`` and ``execute``).  Their raw bucket counts ride
``get_metrics()`` to the controller, which diffs them per tick, federates
across replicas with ``perf.merge_counts``, and publishes per-replica
execute p95 to routers / feeds the EWMA-smoothed SLO autoscaler.
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, List, Optional

from ray_tpu import chaos
from ray_tpu._private.config import _config
from ray_tpu.exceptions import BatchExecutionError, ServeOverloadedError
from ray_tpu.observability import perf
from ray_tpu.serve.batching import next_request_id, pad_items

# EWMA weight for the per-item execution-time estimate that sizes batches
# and the queue_est_ms backpressure signal (local smoothing; the
# autoscaler's cross-tick smoothing uses serve_autoscale_ewma_alpha).
_ITEM_EWMA_ALPHA = 0.3


def _load_checkpoint(checkpoint: Any) -> Any:
    """Resolve a deployment checkpoint to the restored pytree. Accepts a
    CheckpointRef or its dict form (DeploymentConfig rides through
    dataclasses.asdict on deploy)."""
    from ray_tpu.checkpoint import CheckpointRef
    if isinstance(checkpoint, dict) and "root" in checkpoint:
        checkpoint = CheckpointRef(**checkpoint)
    if isinstance(checkpoint, CheckpointRef):
        return checkpoint.load()
    return checkpoint


def _resolve_arg_refs(args):
    """Resolve ObjectRef request arguments to their values.  The proxy
    puts large raw ingress bodies into the object plane and ships a ref
    (the bytes ride the striped transport pool); ``handle_request``'s
    own args tuple is nested inside the actor-call args, so the
    runtime's top-level ref resolution does not reach it — resolve here,
    on the replica's host, where the fetch is local-or-striped."""
    from ray_tpu.object_ref import ObjectRef
    if not any(isinstance(a, ObjectRef) for a in args):
        return args
    import ray_tpu
    return tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                 for a in args)


class _BatchSlot:
    """One queued request parked in the replica batcher."""

    __slots__ = ("item", "event", "value", "error", "request_id",
                 "t_enqueue")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.request_id = next_request_id()
        self.t_enqueue = time.monotonic()


class _ReplicaBatcher:
    """Adaptive micro-batcher owned by one replica (see module docstring
    for the state machine: admit → linger → shed-expired → pad-to-bucket
    execute → per-item deliver)."""

    def __init__(self, replica: "Replica", cfg: dict):
        self._replica = replica
        # the batch shape is retune()-able live (autopilot serve policy),
        # so the flush loop reads it under the same lock as the queue
        # raylint: guarded-by(self._lock)
        self._max = max(1, int(cfg.get("max_batch_size", 1)))
        # raylint: guarded-by(self._lock)
        self._wait_s = float(cfg.get("batch_wait_timeout_s", 0.005))
        pad = cfg.get("pad_batch_to")
        # raylint: guarded-by(self._lock)
        self._buckets = tuple(sorted(int(b) for b in pad)) if pad else None
        self._lock = threading.Lock()
        self._queue: List[_BatchSlot] = []  # raylint: guarded-by(self._lock)
        self._wakeup = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop = False

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def retune(self, cfg: dict) -> None:
        """Live-update the batch shape (autopilot serve policy): the
        next flush cycle reads the new linger/cap; requests already
        parked keep their slots — nothing is dropped on a retune."""
        with self._lock:
            if "max_batch_size" in cfg:
                self._max = max(1, int(cfg["max_batch_size"]))
            if "batch_wait_timeout_s" in cfg:
                self._wait_s = max(0.0, float(cfg["batch_wait_timeout_s"]))
            if "pad_batch_to" in cfg:
                pad = cfg["pad_batch_to"]
                self._buckets = (tuple(sorted(int(b) for b in pad))
                                 if pad else None)
        self._wakeup.set()

    def submit(self, item) -> Any:
        slot = _BatchSlot(item)
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name=f"serve-replica-batch-{self._replica.replica_tag}")
                self._thread.start()
            self._queue.append(slot)
        self._wakeup.set()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.value

    def shutdown(self) -> None:
        self._stop = True
        self._wakeup.set()

    def _effective_max(self) -> int:
        """Latency-guarded batch-size cap: never form a batch whose
        EWMA-predicted execution time (items × per-item estimate) would
        blow the replica's latency budget."""
        with self._lock:
            want = self._max
        budget = self._replica._batch_budget_ms()
        with self._replica._lock:
            ewma = self._replica._ewma_item_ms
        if budget > 0 and ewma > 0:
            want = min(want, max(1, int(budget / ewma)))
        return max(1, want)

    def _flush_loop(self) -> None:
        while True:
            self._wakeup.wait()
            if self._stop:
                return
            cap = self._effective_max()
            # Linger window anchored on the OLDEST queued request: fire
            # when the batch fills (to the adaptive cap) or the oldest
            # request has waited batch_wait_timeout_s.
            while True:
                with self._lock:
                    depth = len(self._queue)
                    oldest = (self._queue[0].t_enqueue
                              if self._queue else None)
                    wait_s = self._wait_s
                if oldest is None:
                    break
                if (depth >= cap
                        or time.monotonic() - oldest >= wait_s):
                    break
                time.sleep(min(0.0005, max(wait_s / 10.0, 1e-4)))
            deadline_ms = float(_config.get("serve_queue_deadline_ms"))
            expired: List[_BatchSlot] = []
            with self._lock:
                if not self._queue:
                    self._wakeup.clear()
                    continue
                if deadline_ms > 0:
                    now = time.monotonic()
                    live: List[_BatchSlot] = []
                    for s in self._queue:
                        if (now - s.t_enqueue) * 1e3 > deadline_ms:
                            expired.append(s)
                        else:
                            live.append(s)
                    self._queue = live
                batch = self._queue[:cap]
                self._queue = self._queue[cap:]
                if not self._queue:
                    self._wakeup.clear()
            for s in expired:
                wait_ms = (time.monotonic() - s.t_enqueue) * 1e3
                self._replica._observe_queue_wait(wait_ms)
                s.error = ServeOverloadedError(
                    f"request {s.request_id} aged {wait_ms:.0f}ms in the "
                    f"replica {self._replica.replica_tag} queue "
                    f"(serve_queue_deadline_ms={deadline_ms:.0f})",
                    retry_after_s=max(deadline_ms / 1e3, 0.1))
                s.event.set()
            if batch:
                self._run_batch(batch)

    def _call(self, items: List[Any]) -> List[Any]:
        n = len(items)
        with self._lock:
            buckets = self._buckets
        padded = pad_items(list(items), buckets)
        results = list(self._replica._invoke_batch(padded))[:n]
        if len(results) != n:
            raise ValueError(
                f"batched deployment returned {len(results)} results "
                f"for {n} inputs")
        return results

    def _run_batch(self, batch: List[_BatchSlot]) -> None:
        r = self._replica
        t_start = time.monotonic()
        for s in batch:
            r._observe_queue_wait((t_start - s.t_enqueue) * 1e3)
        n = len(batch)
        try:
            if chaos.ENABLED:
                chaos.inject("serve.replica.execute",
                             deployment=r.deployment_name,
                             replica=r.replica_tag)
            results = self._call([s.item for s in batch])
            r._observe_execute((time.monotonic() - t_start) * 1e3, n)
            for s, v in zip(batch, results):
                s.value = v
                s.event.set()
            return
        except BaseException as e:
            error = e
        r._observe_execute((time.monotonic() - t_start) * 1e3, n)
        # Per-item error isolation (same policy as serve/batching.py):
        # a singleton's error is unambiguously its own; larger batches
        # re-run members alone once so a poisoned request fails alone,
        # or — with retry off — get a batch-level tag naming size and
        # request ids.
        if n == 1:
            batch[0].error = error
            batch[0].event.set()
            return
        if _config.get("serve_batch_retry_singletons"):
            for s in batch:
                t1 = time.monotonic()
                try:
                    s.value = self._call([s.item])[0]
                except BaseException as single_err:
                    s.error = single_err
                r._observe_execute((time.monotonic() - t1) * 1e3, 1)
                s.event.set()
            return
        tagged = BatchExecutionError(
            getattr(r._callable, "__name__", r.deployment_name),
            n, [s.request_id for s in batch], error)
        for s in batch:
            s.error = tagged
            s.event.set()


class Replica:
    def __init__(self, deployment_name: str, replica_tag: str,
                 func_or_class, init_args, init_kwargs,
                 user_config: Optional[Any] = None,
                 checkpoint: Optional[Any] = None,
                 batch_config: Optional[dict] = None):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._draining = False
        self._is_function = inspect.isfunction(func_or_class)
        if checkpoint is not None:
            if self._is_function:
                # Only class replicas have an __init__ to receive the
                # restored tree; silently dropping the checkpoint would
                # serve uninitialized weights.
                raise ValueError(
                    f"deployment {deployment_name!r}: checkpoint= requires "
                    "a class deployment (the restored pytree is injected "
                    "as the checkpoint= init kwarg); a function replica "
                    "has nowhere to receive it")
            # Cold start from an engine manifest: the weights pytree loads
            # from the content-addressed store HERE, on the replica — the
            # controller only ever shipped the (root, manifest) pointer.
            init_kwargs = dict(init_kwargs or {})
            init_kwargs["checkpoint"] = _load_checkpoint(checkpoint)
        if self._is_function:
            self._callable = func_or_class
        else:
            self._callable = func_or_class(*init_args, **(init_kwargs or {}))
        # Replica-local latency sensors (always on — they are the
        # router/autoscaler inputs, not optional observability).
        self._hist_queue_wait = perf.PerfHistogram("queue_wait")
        self._hist_execute = perf.PerfHistogram("execute")
        self._ewma_item_ms = 0.0  # raylint: guarded-by(self._lock)
        self._batch_cfg = dict(batch_config) if batch_config else None
        self._batcher = self._build_batcher()
        if user_config is not None:
            self.reconfigure(user_config)

    def _build_batcher(self) -> Optional[_ReplicaBatcher]:
        cfg = self._batch_cfg
        if cfg and int(cfg.get("max_batch_size", 1)) > 1:
            return _ReplicaBatcher(self, cfg)
        return None

    def _batch_budget_ms(self) -> float:
        cfg = self._batch_cfg or {}
        target = float(cfg.get("target_latency_ms") or 0.0)
        if target > 0:
            return target
        return float(_config.get("serve_target_latency_ms"))

    def _invoke_batch(self, items: List[Any]):
        # Function deployments and class __call__ share the contract:
        # take a LIST of requests, return a list of equal length.  An
        # async callable is run to completion here — the flusher thread
        # has no event loop of its own, and the result must be a list.
        result = self._callable(items)
        if inspect.iscoroutine(result):
            result = asyncio.run(result)
        return result

    def _observe_queue_wait(self, ms: float) -> None:
        self._hist_queue_wait.observe(ms)
        if perf.ENABLED:
            perf.observe("serve.queue_wait", ms)

    def _observe_execute(self, ms: float, n: int) -> None:
        """Record one batch execution covering ``n`` requests: each
        member experienced the whole batch's wall time, so the execute
        histogram gets ``n`` samples of ``ms``; the per-item EWMA gets
        ``ms / n`` (the amortized cost that sizes future batches)."""
        per_item = ms / max(n, 1)
        with self._lock:
            prev = self._ewma_item_ms
            self._ewma_item_ms = (per_item if prev == 0.0 else
                                  prev + _ITEM_EWMA_ALPHA * (per_item - prev))
        for _ in range(n):
            self._hist_execute.observe(ms)

    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function:
            reconfigure = getattr(self._callable, "reconfigure", None)
            if reconfigure is not None:
                reconfigure(user_config)

    def set_batch_config(self, cfg: dict) -> None:
        """Merge a batch-config delta into the live batcher (the
        controller's ``retune_deployment_batch`` fan-out target)."""
        merged = dict(self._batch_cfg or {})
        merged.update(cfg or {})
        self._batch_cfg = merged
        batcher = self._batcher
        if batcher is not None:
            batcher.retune(merged)
        elif int(merged.get("max_batch_size", 1)) > 1:
            self._batcher = self._build_batcher()

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    f"Replica {self.replica_tag} is draining")
            self._ongoing += 1
            self._total += 1
        try:
            args = _resolve_arg_refs(args)
            batcher = self._batcher
            if (batcher is not None and method_name == "__call__"
                    and len(args) == 1 and not kwargs):
                # The caller's actor thread parks on its slot; queue wait
                # and execute are recorded by the flusher per batch.
                return batcher.submit(args[0])
            t0 = time.monotonic()
            try:
                if chaos.ENABLED:
                    chaos.inject("serve.replica.execute",
                                 deployment=self.deployment_name,
                                 replica=self.replica_tag)
                if self._is_function:
                    return self._callable(*args, **kwargs)
                if method_name == "__call__":
                    return self._callable(*args, **kwargs)
                return getattr(self._callable, method_name)(*args, **kwargs)
            finally:
                ms = (time.monotonic() - t0) * 1e3
                self._observe_queue_wait(0.0)
                self._observe_execute(ms, 1)
                if perf.ENABLED:
                    perf.observe("serve.replica_exec", ms)
        finally:
            with self._lock:
                self._ongoing -= 1

    def get_metrics(self) -> dict:
        qw_counts, qw_sum = self._hist_queue_wait.merged()
        ex_counts, ex_sum = self._hist_execute.merged()
        batcher = self._batcher
        depth = batcher.depth() if batcher is not None else 0
        with self._lock:
            ongoing = self._ongoing
            total = self._total
            ewma_ms = self._ewma_item_ms
        # Estimated time-to-drain of work already admitted here: the
        # router's shed signal and a tiebreaker for scoring.
        pending = depth if batcher is not None else ongoing
        ewma = ewma_ms
        return {"replica_tag": self.replica_tag,
                "num_ongoing_requests": ongoing,
                "num_total_requests": total,
                "queue_depth": depth,
                "queue_est_ms": pending * ewma,
                "ewma_item_ms": ewma,
                "perf": {
                    "bounds": list(perf.bucket_bounds()),
                    "queue_wait": {"counts": qw_counts, "sum_ms": qw_sum},
                    "execute": {"counts": ex_counts, "sum_ms": ex_sum},
                }}

    def check_health(self) -> bool:
        checker = None if self._is_function else getattr(
            self._callable, "check_health", None)
        if checker is not None:
            checker()
        return True

    def prepare_for_shutdown(self, timeout_s: float = 20.0) -> bool:
        """Stop accepting requests and wait for in-flight ones to drain."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    drained = True
                    break
            time.sleep(0.01)
        if self._batcher is not None:
            self._batcher.shutdown()
        return drained

    # A node drain snapshots hosted actors with cloudpickle. The lock, the
    # batcher (thread/event) and the histogram shards (thread-locals) are
    # not picklable and the drain-time flags must not survive migration —
    # a replica restored on a healthy node serves again immediately with
    # fresh sensors and a fresh batcher rebuilt from _batch_cfg.
    def __getstate__(self):
        with self._lock:
            st = self.__dict__.copy()
        st.pop("_lock", None)
        st.pop("_batcher", None)
        st.pop("_hist_queue_wait", None)
        st.pop("_hist_execute", None)
        st["_draining"] = False
        st["_ongoing"] = 0
        st["_ewma_item_ms"] = 0.0
        return st

    def __setstate__(self, st):
        self.__dict__.update(st)
        self._lock = threading.Lock()
        self._hist_queue_wait = perf.PerfHistogram("queue_wait")
        self._hist_execute = perf.PerfHistogram("execute")
        self._batcher = self._build_batcher()
