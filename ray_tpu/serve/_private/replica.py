"""Replica actor: hosts one copy of a deployment's user callable.

Parity with ``python/ray/serve/_private/replica.py``: runs the user class
(or function), counts ongoing requests for autoscaling/backpressure,
supports ``reconfigure(user_config)`` in place, health checks, and
graceful drain before shutdown.

TPU note: a replica is where compiled inference lives — the user callable
typically closes over a ``jax.jit``'d function.  Replicas stay alive across
requests precisely so XLA compilation caches stay warm; a rolling update
replaces replicas one at a time so the app never serves with a cold cache
on every replica at once.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Optional

from ray_tpu.observability import perf


def _load_checkpoint(checkpoint: Any) -> Any:
    """Resolve a deployment checkpoint to the restored pytree. Accepts a
    CheckpointRef or its dict form (DeploymentConfig rides through
    dataclasses.asdict on deploy)."""
    from ray_tpu.checkpoint import CheckpointRef
    if isinstance(checkpoint, dict) and "root" in checkpoint:
        checkpoint = CheckpointRef(**checkpoint)
    if isinstance(checkpoint, CheckpointRef):
        return checkpoint.load()
    return checkpoint


def _resolve_arg_refs(args):
    """Resolve ObjectRef request arguments to their values.  The proxy
    puts large raw ingress bodies into the object plane and ships a ref
    (the bytes ride the striped transport pool); ``handle_request``'s
    own args tuple is nested inside the actor-call args, so the
    runtime's top-level ref resolution does not reach it — resolve here,
    on the replica's host, where the fetch is local-or-striped."""
    from ray_tpu.object_ref import ObjectRef
    if not any(isinstance(a, ObjectRef) for a in args):
        return args
    import ray_tpu
    return tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                 for a in args)


class Replica:
    def __init__(self, deployment_name: str, replica_tag: str,
                 func_or_class, init_args, init_kwargs,
                 user_config: Optional[Any] = None,
                 checkpoint: Optional[Any] = None):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._draining = False
        self._is_function = inspect.isfunction(func_or_class)
        if checkpoint is not None:
            if self._is_function:
                # Only class replicas have an __init__ to receive the
                # restored tree; silently dropping the checkpoint would
                # serve uninitialized weights.
                raise ValueError(
                    f"deployment {deployment_name!r}: checkpoint= requires "
                    "a class deployment (the restored pytree is injected "
                    "as the checkpoint= init kwarg); a function replica "
                    "has nowhere to receive it")
            # Cold start from an engine manifest: the weights pytree loads
            # from the content-addressed store HERE, on the replica — the
            # controller only ever shipped the (root, manifest) pointer.
            init_kwargs = dict(init_kwargs or {})
            init_kwargs["checkpoint"] = _load_checkpoint(checkpoint)
        if self._is_function:
            self._callable = func_or_class
        else:
            self._callable = func_or_class(*init_args, **(init_kwargs or {}))
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function:
            reconfigure = getattr(self._callable, "reconfigure", None)
            if reconfigure is not None:
                reconfigure(user_config)

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    f"Replica {self.replica_tag} is draining")
            self._ongoing += 1
            self._total += 1
        t0 = time.monotonic() if perf.ENABLED else 0.0
        try:
            args = _resolve_arg_refs(args)
            if self._is_function:
                return self._callable(*args, **kwargs)
            if method_name == "__call__":
                return self._callable(*args, **kwargs)
            return getattr(self._callable, method_name)(*args, **kwargs)
        finally:
            if t0:
                perf.observe("serve.replica_exec",
                             (time.monotonic() - t0) * 1e3)
            with self._lock:
                self._ongoing -= 1

    def get_metrics(self) -> dict:
        with self._lock:
            return {"replica_tag": self.replica_tag,
                    "num_ongoing_requests": self._ongoing,
                    "num_total_requests": self._total}

    def check_health(self) -> bool:
        checker = None if self._is_function else getattr(
            self._callable, "check_health", None)
        if checker is not None:
            checker()
        return True

    def prepare_for_shutdown(self, timeout_s: float = 20.0) -> bool:
        """Stop accepting requests and wait for in-flight ones to drain."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.01)
        return False

    # A node drain snapshots hosted actors with cloudpickle. The lock is
    # not picklable and the drain-time flags must not survive migration —
    # a replica restored on a healthy node serves again immediately.
    def __getstate__(self):
        with self._lock:
            st = self.__dict__.copy()
        st.pop("_lock", None)
        st["_draining"] = False
        st["_ongoing"] = 0
        return st

    def __setstate__(self, st):
        self.__dict__.update(st)
        self._lock = threading.Lock()
