"""Deployment reconciliation: target state -> running replica actors.

Parity with ``python/ray/serve/_private/deployment_state.py``: each
deployment has a target (code version, config, replica count); a reconcile
step starts/stops replica actors to converge, performs rolling updates when
the code version changes, reconfigures in place when only user_config
changes, and replaces dead replicas.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve._private.replica import Replica
from ray_tpu.serve.config import DeploymentConfig

logger = logging.getLogger("ray_tpu.serve")

_replica_counter = itertools.count()


class ReplicaInfo:
    healthy = False  # flips on the first successful health probe

    def __init__(self, tag: str, handle, version: str):
        self.tag = tag
        self.handle = handle
        self.version = version


class DeploymentState:
    def __init__(self, name: str):
        self.name = name
        self.func_or_class = None
        self.init_args: Tuple = ()
        self.init_kwargs: Dict = {}
        self.config = DeploymentConfig()
        self.target_version: Optional[str] = None
        self.target_replicas = 0
        self.replicas: List[ReplicaInfo] = []
        self.deleting = False
        self._last_health_check = 0.0

    # -- target mutations -------------------------------------------------

    def set_target(self, func_or_class, init_args, init_kwargs,
                   config: DeploymentConfig) -> None:
        self.func_or_class = func_or_class
        self.init_args = init_args or ()
        self.init_kwargs = init_kwargs or {}
        new_version = config.version_hash(
            func_or_class, self.init_args, self.init_kwargs)
        version_changed = new_version != self.target_version
        user_config_changed = config.user_config != self.config.user_config
        self.target_version = new_version
        self.config = config
        self.target_replicas = (
            config.autoscaling_config.min_replicas
            if config.autoscaling_config else config.num_replicas)
        self.deleting = False
        if not version_changed and user_config_changed:
            # In-place reconfigure (reference: lightweight config update).
            for info in self.replicas:
                try:
                    ray_tpu.get(info.handle.reconfigure.remote(
                        config.user_config))
                except Exception as e:
                    logger.warning("in-place reconfigure failed: %s", e)

    def set_num_replicas(self, n: int) -> None:
        cfg = self.config.autoscaling_config
        if cfg is not None:
            n = max(cfg.min_replicas, min(cfg.max_replicas, n))
        self.target_replicas = n

    def delete(self) -> None:
        self.deleting = True
        self.target_replicas = 0

    # -- reconciliation ---------------------------------------------------

    def _start_replica(self) -> ReplicaInfo:
        tag = f"{self.name}#{next(_replica_counter)}"
        opts = dict(self.config.ray_actor_options)
        opts.setdefault("max_concurrency",
                        max(2, self.config.max_concurrent_queries))
        handle = ray_tpu.remote(Replica).options(**opts).remote(
            self.name, tag, self.func_or_class, self.init_args,
            self.init_kwargs, self.config.user_config,
            self.config.checkpoint)
        return ReplicaInfo(tag, handle, self.target_version)

    def _stop_replica(self, info: ReplicaInfo) -> None:
        try:
            ray_tpu.get(info.handle.prepare_for_shutdown.remote(
                self.config.graceful_shutdown_timeout_s), timeout=None)
        except Exception as e:
            logger.debug("graceful replica shutdown failed: %s", e)
        try:
            ray_tpu.kill(info.handle)
        except Exception as e:
            logger.debug("replica kill failed: %s", e)

    def _check_health(self) -> List[ReplicaInfo]:
        """Probe all replicas concurrently; returns the live ones.

        A replica is dead only when its health ref resolves to an error
        (actor died); a slow-but-running replica whose ref isn't ready
        within the probe window stays live.  Runs at
        ``health_check_period_s`` cadence, not every control-loop tick.
        """
        import time as _time
        probes = []
        for info in self.replicas:
            try:
                probes.append((info, info.handle.check_health.remote()))
            except Exception as e:
                logger.debug("health probe submit failed: %s", e)
                probes.append((info, None))
        refs = [r for _, r in probes if r is not None]
        if refs:
            ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
        live = []
        for info, ref in probes:
            if ref is None:
                logger.warning("replica %s unreachable; replacing", info.tag)
                continue
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if not ready:
                live.append(info)  # slow, not dead
                continue
            try:
                ray_tpu.get(ref, timeout=0.1)
                info.healthy = True   # answered a probe: READY to serve
                live.append(info)
            except Exception:
                logger.warning("replica %s died; replacing", info.tag)
        self._last_health_check = _time.monotonic()
        return live

    def reconcile(self) -> bool:
        """One convergence step. Returns True if replica membership changed."""
        import time as _time
        changed = False

        # Replace dead replicas (failure recovery) on the configured
        # cadence — but while any replica has never answered a probe
        # (still placing / initializing), probe EVERY tick so readiness
        # (serve.run's wait) resolves promptly.
        if self.replicas and (
                any(not r.healthy for r in self.replicas)
                or _time.monotonic() - self._last_health_check
                >= self.config.health_check_period_s):
            live = self._check_health()
            if len(live) != len(self.replicas):
                changed = True
            self.replicas = live

        # Rolling update: retire at most one stale replica per step so
        # capacity never drops by more than one (reference semantics).
        stale = [r for r in self.replicas if r.version != self.target_version]
        if stale and self.func_or_class is not None:
            old = stale[0]
            if len(self.replicas) <= self.target_replicas:
                self.replicas.append(self._start_replica())
            self.replicas.remove(old)
            self._stop_replica(old)
            changed = True

        # Scale toward the target count.
        while len(self.replicas) < self.target_replicas:
            self.replicas.append(self._start_replica())
            changed = True
        while len(self.replicas) > self.target_replicas:
            info = self.replicas.pop()
            self._stop_replica(info)
            changed = True
        return changed

    # -- introspection ----------------------------------------------------

    def running_replica_handles(self) -> List[Any]:
        return [r.handle for r in self.replicas]

    def total_ongoing_requests(self) -> float:
        total = 0.0
        for info in self.replicas:
            try:
                m = ray_tpu.get(info.handle.get_metrics.remote(), timeout=5)
                total += m["num_ongoing_requests"]
            except Exception as e:
                logger.debug("replica metrics fetch failed: %s", e)
        return total

    def status(self) -> dict:
        return {
            "name": self.name,
            "target_replicas": self.target_replicas,
            "running_replicas": len(self.replicas),
            # replicas that have ANSWERED a health probe — running counts
            # only started handles, whose actors may still be placing or
            # initializing (serve.run readiness waits on this)
            "ready_replicas": sum(1 for r in self.replicas if r.healthy),
            "version": self.target_version,
            "deleting": self.deleting,
        }
