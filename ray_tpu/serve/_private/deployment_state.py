"""Deployment reconciliation: target state -> running replica actors.

Parity with ``python/ray/serve/_private/deployment_state.py``: each
deployment has a target (code version, config, replica count); a reconcile
step starts/stops replica actors to converge, performs rolling updates when
the code version changes, reconfigures in place when only user_config
changes, and replaces dead replicas.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.observability import perf
from ray_tpu.serve._private.replica import Replica
from ray_tpu.serve.config import DeploymentConfig

logger = logging.getLogger("ray_tpu.serve")

_replica_counter = itertools.count()


class ReplicaInfo:
    healthy = False  # flips on the first successful health probe

    def __init__(self, tag: str, handle, version: str):
        self.tag = tag
        self.handle = handle
        self.version = version


class DeploymentState:
    def __init__(self, name: str):
        self.name = name
        self.func_or_class = None
        self.init_args: Tuple = ()
        self.init_kwargs: Dict = {}
        self.config = DeploymentConfig()
        self.target_version: Optional[str] = None
        self.target_replicas = 0
        self.replicas: List[ReplicaInfo] = []
        self.deleting = False
        self._last_health_check = 0.0
        # Last-seen cumulative perf counts per replica tag: the controller
        # federates WINDOWED (per-tick delta) histograms, so each tick's
        # p95 reflects recent traffic, not all history.
        self._prev_perf: Dict[str, Dict[str, List[int]]] = {}

    # -- target mutations -------------------------------------------------

    def set_target(self, func_or_class, init_args, init_kwargs,
                   config: DeploymentConfig) -> None:
        self.func_or_class = func_or_class
        self.init_args = init_args or ()
        self.init_kwargs = init_kwargs or {}
        new_version = config.version_hash(
            func_or_class, self.init_args, self.init_kwargs)
        version_changed = new_version != self.target_version
        user_config_changed = config.user_config != self.config.user_config
        self.target_version = new_version
        self.config = config
        self.target_replicas = (
            config.autoscaling_config.min_replicas
            if config.autoscaling_config else config.num_replicas)
        self.deleting = False
        if not version_changed and user_config_changed:
            # In-place reconfigure (reference: lightweight config update).
            for info in self.replicas:
                try:
                    ray_tpu.get(info.handle.reconfigure.remote(
                        config.user_config))
                except Exception as e:
                    logger.warning("in-place reconfigure failed: %s", e)

    def set_num_replicas(self, n: int) -> None:
        cfg = self.config.autoscaling_config
        if cfg is not None:
            n = max(cfg.min_replicas, min(cfg.max_replicas, n))
        self.target_replicas = n

    def delete(self) -> None:
        self.deleting = True
        self.target_replicas = 0

    def retune_batch(self, **cfg: Any) -> None:
        """Push a batch-config delta (linger, cap, pad buckets) to every
        live replica AND into the target config, so replicas started
        later inherit the retuned shape.  This is the serve actuator's
        write path — the autopilot tunes linger here from the federated
        ``serve.queue_wait`` p95, journaled like every other knob."""
        for key, value in cfg.items():
            if hasattr(self.config, key):
                setattr(self.config, key, value)
        for info in self.replicas:
            try:
                ray_tpu.get(info.handle.set_batch_config.remote(dict(cfg)))
            except Exception as e:  # noqa: BLE001 — next reconcile replaces
                logger.warning("batch retune of %s failed: %s",
                               info.tag, e)

    # -- reconciliation ---------------------------------------------------

    def _start_replica(self) -> ReplicaInfo:
        tag = f"{self.name}#{next(_replica_counter)}"
        opts = dict(self.config.ray_actor_options)
        opts.setdefault("max_concurrency",
                        max(2, self.config.max_concurrent_queries))
        batch_cfg = None
        if getattr(self.config, "max_batch_size", 1) > 1:
            batch_cfg = {
                "max_batch_size": self.config.max_batch_size,
                "batch_wait_timeout_s": self.config.batch_wait_timeout_s,
                "pad_batch_to": self.config.pad_batch_to,
                "target_latency_ms": self.config.target_latency_ms,
            }
        handle = ray_tpu.remote(Replica).options(**opts).remote(
            self.name, tag, self.func_or_class, self.init_args,
            self.init_kwargs, self.config.user_config,
            self.config.checkpoint, batch_cfg)
        return ReplicaInfo(tag, handle, self.target_version)

    def _stop_replica(self, info: ReplicaInfo) -> None:
        try:
            ray_tpu.get(info.handle.prepare_for_shutdown.remote(
                self.config.graceful_shutdown_timeout_s), timeout=None)
        except Exception as e:
            logger.debug("graceful replica shutdown failed: %s", e)
        try:
            ray_tpu.kill(info.handle)
        except Exception as e:
            logger.debug("replica kill failed: %s", e)

    def _check_health(self) -> List[ReplicaInfo]:
        """Probe all replicas concurrently; returns the live ones.

        A replica is dead only when its health ref resolves to an error
        (actor died); a slow-but-running replica whose ref isn't ready
        within the probe window stays live.  Runs at
        ``health_check_period_s`` cadence, not every control-loop tick.
        """
        import time as _time
        probes = []
        for info in self.replicas:
            try:
                probes.append((info, info.handle.check_health.remote()))
            except Exception as e:
                logger.debug("health probe submit failed: %s", e)
                probes.append((info, None))
        refs = [r for _, r in probes if r is not None]
        if refs:
            ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
        live = []
        for info, ref in probes:
            if ref is None:
                logger.warning("replica %s unreachable; replacing", info.tag)
                continue
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if not ready:
                live.append(info)  # slow, not dead
                continue
            try:
                ray_tpu.get(ref, timeout=0.1)
                info.healthy = True   # answered a probe: READY to serve
                live.append(info)
            except Exception:
                logger.warning("replica %s died; replacing", info.tag)
        self._last_health_check = _time.monotonic()
        return live

    def reconcile(self) -> bool:
        """One convergence step. Returns True if replica membership changed."""
        import time as _time
        changed = False

        # Replace dead replicas (failure recovery) on the configured
        # cadence — but while any replica has never answered a probe
        # (still placing / initializing), probe EVERY tick so readiness
        # (serve.run's wait) resolves promptly.
        if self.replicas and (
                any(not r.healthy for r in self.replicas)
                or _time.monotonic() - self._last_health_check
                >= self.config.health_check_period_s):
            live = self._check_health()
            if len(live) != len(self.replicas):
                changed = True
            self.replicas = live

        # Rolling update: retire at most one stale replica per step so
        # capacity never drops by more than one (reference semantics).
        stale = [r for r in self.replicas if r.version != self.target_version]
        if stale and self.func_or_class is not None:
            old = stale[0]
            if len(self.replicas) <= self.target_replicas:
                self.replicas.append(self._start_replica())
            self.replicas.remove(old)
            self._stop_replica(old)
            changed = True

        # Scale toward the target count.
        while len(self.replicas) < self.target_replicas:
            self.replicas.append(self._start_replica())
            changed = True
        while len(self.replicas) > self.target_replicas:
            info = self.replicas.pop()
            self._stop_replica(info)
            changed = True
        return changed

    # -- introspection ----------------------------------------------------

    def running_replica_handles(self) -> List[Any]:
        return [r.handle for r in self.replicas]

    def total_ongoing_requests(self) -> float:
        total = 0.0
        for info in self.replicas:
            try:
                m = ray_tpu.get(info.handle.get_metrics.remote(), timeout=5)
                total += m["num_ongoing_requests"]
            except Exception as e:
                logger.debug("replica metrics fetch failed: %s", e)
        return total

    @staticmethod
    def _window(cur: Optional[List[int]],
                prev: Optional[List[int]]) -> Optional[List[int]]:
        """Per-bucket delta of cumulative counts since the last tick.
        A restarted replica's counts reset below the previous snapshot —
        clamp at 0 instead of producing negative buckets."""
        if not cur:
            return None
        if not prev or len(prev) != len(cur):
            return list(cur)
        return [max(0, c - p) for c, p in zip(cur, prev)]

    def collect_metrics(self) -> dict:
        """One federated sensor sweep: fetch every replica's local
        histograms, window them against the previous tick, and compute

        - per-replica windowed ``execute`` p95 (published to routers for
          power-of-two-choices scoring) and ``queue_est_ms`` backpressure,
        - the deployment-wide windowed ``queue_wait`` + ``execute`` p95
          (summed: the time a newly admitted request should expect) that
          drives the SLO autoscaler,
        - total ongoing requests (the legacy queue-depth signal), all
          from a single ``get_metrics`` round-trip per replica.
        """
        probes = []
        for info in self.replicas:
            try:
                probes.append((info, info.handle.get_metrics.remote()))
            except Exception as e:
                logger.debug("replica metrics submit failed: %s", e)
        total_ongoing = 0.0
        per_replica: Dict[str, dict] = {}
        qw_windows: List[List[int]] = []
        ex_windows: List[List[int]] = []
        bounds = None
        new_prev: Dict[str, Dict[str, List[int]]] = {}
        for info, ref in probes:
            try:
                m = ray_tpu.get(ref, timeout=5)
            except Exception as e:
                logger.debug("replica metrics fetch failed: %s", e)
                continue
            total_ongoing += m.get("num_ongoing_requests", 0)
            p = m.get("perf") or {}
            bounds = p.get("bounds") or bounds
            qw = (p.get("queue_wait") or {}).get("counts")
            ex = (p.get("execute") or {}).get("counts")
            prev = self._prev_perf.get(info.tag, {})
            d_qw = self._window(qw, prev.get("queue_wait"))
            d_ex = self._window(ex, prev.get("execute"))
            new_prev[info.tag] = {"queue_wait": list(qw or []),
                                  "execute": list(ex or [])}
            exec_p95 = (perf.quantile(d_ex, 0.95, bounds)
                        if d_ex and sum(d_ex) else 0.0)
            per_replica[info.tag] = {
                "p95_ms": exec_p95,
                "queue_est_ms": float(m.get("queue_est_ms", 0.0)),
                "ongoing": int(m.get("num_ongoing_requests", 0)),
            }
            if d_qw:
                qw_windows.append(d_qw)
            if d_ex:
                ex_windows.append(d_ex)
        self._prev_perf = new_prev
        p95 = 0.0
        merged_qw = perf.merge_counts(qw_windows)
        if merged_qw and sum(merged_qw):
            p95 += perf.quantile(merged_qw, 0.95, bounds)
        merged_ex = perf.merge_counts(ex_windows)
        if merged_ex and sum(merged_ex):
            p95 += perf.quantile(merged_ex, 0.95, bounds)
        return {"total_ongoing": total_ongoing,
                "replicas": per_replica,
                "p95_ms": p95}

    def status(self) -> dict:
        return {
            "name": self.name,
            "target_replicas": self.target_replicas,
            "running_replicas": len(self.replicas),
            # replicas that have ANSWERED a health probe — running counts
            # only started handles, whose actors may still be placing or
            # initializing (serve.run readiness waits on this)
            "ready_replicas": sum(1 for r in self.replicas if r.healthy),
            "version": self.target_version,
            "deleting": self.deleting,
        }
