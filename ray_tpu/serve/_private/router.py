"""Request router: picks a replica for each request.

Parity with ``python/ray/serve/_private/router.py``: round-robin over
running replicas while honoring ``max_concurrent_queries`` per replica —
requests beyond the limit queue in the router until a replica frees up.
Replica membership updates arrive via long-poll from the controller.
"""

from __future__ import annotations
import logging

import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve.controller import _replica_key

logger = logging.getLogger("ray_tpu")


class Router:
    def __init__(self, controller_handle, deployment_name: str):
        self._deployment_name = deployment_name
        self._controller = controller_handle
        self._lock = threading.Condition()
        self._replicas: List[Any] = []
        self._max_concurrent = 100
        self._in_flight: Dict[str, int] = {}  # replica repr -> count
        self._rr = 0
        # Seed synchronously so the first request doesn't race the poller.
        info = ray_tpu.get(
            controller_handle.get_replica_handles.remote(deployment_name))
        self._apply(info)
        self._poller = LongPollClient(
            controller_handle,
            {_replica_key(deployment_name): self._apply})

    def _apply(self, info: dict) -> None:
        with self._lock:
            self._replicas = list(info["handles"])
            self._max_concurrent = info["max_concurrent_queries"]
            # Drop in-flight counters for replicas no longer in membership
            # so the dict doesn't grow without bound under churn.
            current = {repr(r) for r in self._replicas}
            self._in_flight = {k: v for k, v in self._in_flight.items()
                               if k in current}
            self._lock.notify_all()

    def _pick(self, timeout: Optional[float]) -> Any:
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                n = len(self._replicas)
                for i in range(n):
                    replica = self._replicas[(self._rr + i) % n] if n else None
                    if replica is None:
                        break
                    key = repr(replica)
                    if self._in_flight.get(key, 0) < self._max_concurrent:
                        self._rr = (self._rr + i + 1) % n
                        self._in_flight[key] = self._in_flight.get(key, 0) + 1
                        return replica
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"No replica of {self._deployment_name!r} available "
                        f"within timeout")
                self._lock.wait(remaining if remaining is not None else 1.0)

    def _release(self, replica) -> None:
        with self._lock:
            key = repr(replica)
            self._in_flight[key] = max(0, self._in_flight.get(key, 0) - 1)
            self._lock.notify_all()

    def assign_request(self, method_name: str, args, kwargs,
                       timeout: Optional[float] = None):
        """Submit to a replica; returns the ObjectRef of the result.

        The replica slot is released when the result is consumed via
        ``resolve`` (or eagerly on submit failure).
        """
        replica = self._pick(timeout)
        try:
            ref = replica.handle_request.remote(method_name, args, kwargs)
        except Exception:
            self._release(replica)
            raise
        return _TrackedRef(ref, self, replica, (method_name, args, kwargs))

    def _refresh_membership(self) -> None:
        """Pull current replicas from the controller (used on retry, when
        the long-poll update may not have landed yet)."""
        try:
            info = ray_tpu.get(self._controller.get_replica_handles.remote(
                self._deployment_name), timeout=10)
            self._apply(info)
        except Exception as e:
            logger.debug("membership refresh failed: %s", e)

    def shutdown(self) -> None:
        self._poller.stop()


class _TrackedRef:
    """An in-flight request: resolves to the result, releasing its slot.

    If the chosen replica dies before completing (e.g. it was retired by a
    rolling update or crashed), the request is transparently re-assigned to
    another replica, like the reference router's dead-replica retry.
    """

    _MAX_RETRIES = 3

    def __init__(self, ref, router: Router, replica, request):
        self._ref = ref
        self._router = router
        self._replica = replica
        self._request = request
        self._released = False
        self._retries = 0

    def _settle(self) -> None:
        if not self._released:
            self._released = True
            self._router._release(self._replica)

    def result(self, timeout: Optional[float] = None):
        import ray_tpu.exceptions as exc
        while True:
            try:
                value = ray_tpu.get(self._ref, timeout=timeout)
            except ray_tpu.GetTimeoutError:
                # Still executing on the replica — keep its concurrency
                # slot so backpressure stays correct; a later result()
                # call settles it.
                raise
            except Exception as e:
                # Replica death / retirement is retryable on another
                # replica: the request never completed. (User exceptions
                # arrive wrapped in TaskError and are not retried, except
                # the replica's own "draining" rejection.)
                retryable = isinstance(
                    e, (exc.ActorDiedError, exc.ObjectLostError)) or \
                    "is draining" in str(e)
                self._settle()
                if not retryable or self._retries >= self._MAX_RETRIES:
                    raise
                self._retries += 1
                self._router._refresh_membership()
                replaced = self._router.assign_request(
                    *self._request, timeout=30)
                self._ref = replaced._ref
                self._replica = replaced._replica
                self._released = False
                continue
            self._settle()
            return value

    def ref(self):
        """Expose the raw ObjectRef (releases the slot immediately —
        callers managing refs directly opt out of backpressure)."""
        if not self._released:
            self._released = True
            self._router._release(self._replica)
        return self._ref
