"""Request router: picks a replica for each request.

Latency-aware power-of-two-choices (reference router semantics plus the
"join the shorter of two random queues" result): each pick samples two
candidate replicas and takes the one with the lower score

    (in_flight + 1) * max(execute_p95_ms, 0.1)

where ``execute_p95_ms`` is the replica's recently observed (windowed)
execute p95, published by the controller in the long-poll membership
payload.  A replica serving slow — overloaded, chaos-delayed, on a sick
host — scores itself out of rotation without any router-to-router
coordination, while two-choice sampling keeps the herd from stampeding
the single best replica.

Overload control, layered:

- ``max_concurrent_queries`` per replica still bounds admission; requests
  beyond it queue in the router (bounded by ``serve_queue_deadline_ms``
  now, so a shed is a fast 503 upstream, never a hang).
- A per-replica :class:`CircuitBreaker` (via ``BreakerBoard``) opens after
  consecutive delivery failures; open replicas leave the candidate set.
- When EVERY replica's published queue estimate exceeds the deployment's
  latency budget (or its breaker is open), the router sheds immediately
  with :class:`ServeOverloadedError` — the proxy maps it to 503 with
  Retry-After instead of letting the queue grow without bound.
"""

from __future__ import annotations
import logging

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.backoff import OPEN, BreakerBoard
from ray_tpu._private.config import _config
from ray_tpu.exceptions import ServeOverloadedError
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve.controller import _replica_key

logger = logging.getLogger("ray_tpu")

# Floor for the p95 factor in the score: a replica with no observations
# yet (or a genuinely sub-0.1ms one) must not multiply to zero, or
# in-flight load would stop mattering for it entirely.
_P95_FLOOR_MS = 0.1


class Router:
    def __init__(self, controller_handle, deployment_name: str):
        self._deployment_name = deployment_name
        self._controller = controller_handle
        self._lock = threading.Condition()
        self._replicas: List[Any] = []
        self._tags: List[str] = []
        self._max_concurrent = 100
        self._in_flight: Dict[str, int] = {}  # replica tag -> count
        self._p95_ms: Dict[str, float] = {}
        self._queue_est_ms: Dict[str, float] = {}
        self._target_latency_ms = 0.0
        # Per-replica fail-fast: consecutive delivery failures open the
        # breaker and take the replica out of the candidate set until the
        # reset window elapses (then the next pick is the half-open probe).
        self._breakers = BreakerBoard()
        # Seed synchronously so the first request doesn't race the poller.
        info = ray_tpu.get(
            controller_handle.get_replica_handles.remote(deployment_name))
        self._apply(info)
        self._poller = LongPollClient(
            controller_handle,
            {_replica_key(deployment_name): self._apply})

    def _apply(self, info: dict) -> None:
        with self._lock:
            self._replicas = list(info["handles"])
            tags = info.get("tags")
            self._tags = (list(tags) if tags
                          else [repr(r) for r in self._replicas])
            self._max_concurrent = info["max_concurrent_queries"]
            self._target_latency_ms = float(
                info.get("target_latency_ms", 0.0))
            self._p95_ms = dict(info.get("p95_ms") or {})
            self._queue_est_ms = dict(info.get("queue_est_ms") or {})
            # Drop in-flight counters and breakers for replicas no longer
            # in membership so state doesn't grow without bound under
            # churn.
            current = set(self._tags)
            for stale in [t for t in self._in_flight if t not in current]:
                del self._in_flight[stale]
                self._breakers.drop(stale)
            self._lock.notify_all()

    # -- scoring -----------------------------------------------------------

    def _score(self, tag: str) -> float:
        in_flight = self._in_flight.get(tag, 0)
        p95 = max(self._p95_ms.get(tag, 0.0), _P95_FLOOR_MS)
        return (in_flight + 1) * p95

    def _overloaded(self, tag: str, budget_ms: float) -> bool:
        if self._breakers.get(tag).state == OPEN:
            return True
        return budget_ms > 0 and self._queue_est_ms.get(tag, 0.0) > budget_ms

    def _pick(self, timeout: Optional[float]) -> Tuple[Any, str]:
        if timeout is None:
            # "Never hangs": an unbounded pick turns total overload into a
            # stuck caller.  Reuse the queue-deadline budget as the
            # router-side bound (<= 0 keeps the legacy wait-forever).
            deadline_ms = float(_config.get("serve_queue_deadline_ms"))
            timeout = deadline_ms / 1e3 if deadline_ms > 0 else None
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                n = len(self._replicas)
                if n:
                    budget = self._target_latency_ms
                    if all(self._overloaded(t, budget) for t in self._tags):
                        raise ServeOverloadedError(
                            f"all {n} replicas of "
                            f"{self._deployment_name!r} exceed their "
                            f"latency budget ({budget:.0f}ms); shedding",
                            retry_after_s=max(budget / 1e3, 0.1))
                    candidates = [
                        i for i, t in enumerate(self._tags)
                        if self._in_flight.get(t, 0) < self._max_concurrent
                        and self._breakers.get(t).state != OPEN]
                    if candidates:
                        # Power of two choices: sample two, keep the
                        # better-scored one.
                        if len(candidates) > 2:
                            candidates = random.sample(candidates, 2)
                        best = min(candidates,
                                   key=lambda i: self._score(self._tags[i]))
                        tag = self._tags[best]
                        self._in_flight[tag] = \
                            self._in_flight.get(tag, 0) + 1
                        return self._replicas[best], tag
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"No replica of {self._deployment_name!r} available "
                        f"within timeout")
                self._lock.wait(remaining if remaining is not None else 1.0)

    def _release(self, tag: str) -> None:
        with self._lock:
            self._in_flight[tag] = max(0, self._in_flight.get(tag, 0) - 1)
            self._lock.notify_all()

    def assign_request(self, method_name: str, args, kwargs,
                       timeout: Optional[float] = None):
        """Submit to a replica; returns the ObjectRef of the result.

        The replica slot is released when the result is consumed via
        ``resolve`` (or eagerly on submit failure).
        """
        replica, tag = self._pick(timeout)
        try:
            ref = replica.handle_request.remote(method_name, args, kwargs)
        except Exception:
            self._release(tag)
            raise
        return _TrackedRef(ref, self, replica, tag,
                           (method_name, args, kwargs))

    def _refresh_membership(self) -> None:
        """Pull current replicas from the controller (used on retry, when
        the long-poll update may not have landed yet)."""
        try:
            info = ray_tpu.get(self._controller.get_replica_handles.remote(
                self._deployment_name), timeout=10)
            self._apply(info)
        except Exception as e:
            logger.debug("membership refresh failed: %s", e)

    def shutdown(self) -> None:
        self._poller.stop()


class _TrackedRef:
    """An in-flight request: resolves to the result, releasing its slot.

    If the chosen replica dies before completing (e.g. it was retired by a
    rolling update or crashed), the request is transparently re-assigned to
    another replica, like the reference router's dead-replica retry.
    Delivery outcomes feed the router's per-replica circuit breaker: only
    replica-death/retirement counts as a failure — a user exception is a
    healthy replica faithfully reporting bad input.
    """

    _MAX_RETRIES = 3

    def __init__(self, ref, router: Router, replica, tag: str, request):
        self._ref = ref
        self._router = router
        self._replica = replica
        self._tag = tag
        self._request = request
        self._released = False
        self._retries = 0

    def _settle(self) -> None:
        if not self._released:
            self._released = True
            self._router._release(self._tag)

    def result(self, timeout: Optional[float] = None):
        import ray_tpu.exceptions as exc
        while True:
            try:
                value = ray_tpu.get(self._ref, timeout=timeout)
            except ray_tpu.GetTimeoutError:
                # Still executing on the replica — keep its concurrency
                # slot so backpressure stays correct; a later result()
                # call settles it.
                raise
            except Exception as e:
                # Replica death / retirement is retryable on another
                # replica: the request never completed. (User exceptions
                # arrive wrapped in TaskError and are not retried, except
                # the replica's own "draining" rejection.)
                retryable = isinstance(
                    e, (exc.ActorDiedError, exc.ObjectLostError)) or \
                    "is draining" in str(e)
                self._settle()
                if retryable:
                    self._router._breakers.record_failure(self._tag)
                if not retryable or self._retries >= self._MAX_RETRIES:
                    raise
                self._retries += 1
                self._router._refresh_membership()
                replaced = self._router.assign_request(
                    *self._request, timeout=30)
                self._ref = replaced._ref
                self._replica = replaced._replica
                self._tag = replaced._tag
                self._released = False
                continue
            self._settle()
            self._router._breakers.record_success(self._tag)
            return value

    def ref(self):
        """Expose the raw ObjectRef (releases the slot immediately —
        callers managing refs directly opt out of backpressure)."""
        if not self._released:
            self._released = True
            self._router._release(self._tag)
        return self._ref
