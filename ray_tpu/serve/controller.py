"""The Serve controller actor.

Parity with ``python/ray/serve/controller.py`` (``ServeController``
``:59,225``): the single control-loop actor that owns all deployment
targets, reconciles them to running replica actors
(`_private/deployment_state.py`), drives queue-metric autoscaling, and
pushes routing tables to handles/proxies via long-poll
(`_private/long_poll.py`).
"""

from __future__ import annotations
import logging

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private.config import _config
from ray_tpu.serve._private.deployment_state import DeploymentState
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve.config import DeploymentConfig

logger = logging.getLogger("ray_tpu")

CONTROLLER_NAME = "SERVE_CONTROLLER"
ROUTE_TABLE_KEY = "route_table"


def _replica_key(deployment_name: str) -> str:
    return f"replicas::{deployment_name}"


class ServeController:
    def __init__(self, control_loop_period_s: float = 0.2):
        self._deployments: Dict[str, DeploymentState] = {}  # raylint: guarded-by(self._lock)
        self._routes: Dict[str, str] = {}  # route prefix -> deployment name
        self._long_poll = LongPollHost()
        self._lock = threading.RLock()
        self._period = control_loop_period_s
        self._shutdown = threading.Event()
        self._autoscale_state: Dict[str, float] = {}  # raylint: guarded-by(self._lock)
        self._loop_thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-control-loop")
        self._loop_thread.start()

    # -- deploy API --------------------------------------------------------

    def deploy(self, name: str, func_or_class, init_args, init_kwargs,
               config_dict: dict, route_prefix: Optional[str] = None) -> None:
        config = DeploymentConfig(**config_dict)
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                state = self._deployments[name] = DeploymentState(name)
            state.set_target(func_or_class, init_args, init_kwargs, config)
            if route_prefix is not None:
                # A deployment owns one route: drop any previous prefix so a
                # retired route stops serving.
                self._routes = {p: d for p, d in self._routes.items()
                                if d != name}
                self._routes[route_prefix] = name
                self._long_poll.notify_changed(
                    ROUTE_TABLE_KEY, dict(self._routes))
            state.reconcile()
            self._notify_replicas(state)
        self._register_autopilot_actuators(name, config)

    def delete_deployment(self, name: str) -> None:
        self._unregister_autopilot_actuators(name)
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return
            state.delete()
            state.reconcile()
            self._notify_replicas(state)
            del self._deployments[name]
            for suffix in (":up", ":down", ":ewma"):
                self._autoscale_state.pop(f"{name}{suffix}", None)
            self._routes = {p: d for p, d in self._routes.items()
                            if d != name}
            self._long_poll.notify_changed(ROUTE_TABLE_KEY, dict(self._routes))

    def retune_deployment_batch(self, name: str, **cfg: Any) -> None:
        """Live batch retune (autopilot serve actuator target): pushes
        the delta to every running replica and into the target config."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                raise KeyError(f"No deployment named {name!r}")
            state.retune_batch(**cfg)

    # -- autopilot actuators ----------------------------------------------

    def _register_autopilot_actuators(self, name: str, config) -> None:
        """Expose the deployment's micro-batch shape to the autopilot:
        ``serve.<name>.linger_ms`` (the batch linger window, actuated
        from the federated queue_wait p95) and
        ``serve.<name>.max_batch_size``.  Only batched deployments are
        exposed, and only when the controller is autopilot-enabled —
        unregistered knobs are invisible to the policy layer."""
        if getattr(config, "max_batch_size", 1) <= 1 \
                or not _config.get("autopilot_enabled"):
            return
        from ray_tpu.autopilot import actuators as _actuators

        def _get_linger(n=name):
            with self._lock:
                state = self._deployments.get(n)
                return (float(state.config.batch_wait_timeout_s) * 1e3
                        if state else 0.0)

        def _set_linger(ms, n=name):
            self.retune_deployment_batch(
                n, batch_wait_timeout_s=float(ms) / 1e3)

        def _get_max(n=name):
            with self._lock:
                state = self._deployments.get(n)
                return int(state.config.max_batch_size) if state else 1

        def _set_max(v, n=name):
            self.retune_deployment_batch(n, max_batch_size=int(v))

        reg = _actuators.registry()
        reg.register(_actuators.Actuator(
            name=f"serve.{name}.linger_ms", get=_get_linger,
            set=_set_linger, kind="float", lo=1.0, hi=1000.0))
        reg.register(_actuators.Actuator(
            name=f"serve.{name}.max_batch_size", get=_get_max,
            set=_set_max, kind="int", lo=1, hi=1024))

    def _unregister_autopilot_actuators(self, name: str) -> None:
        from ray_tpu.autopilot import actuators as _actuators
        reg = _actuators.registry()
        reg.unregister(f"serve.{name}.linger_ms")
        reg.unregister(f"serve.{name}.max_batch_size")

    def _membership_info(self, state: DeploymentState,
                         metrics: Optional[dict] = None) -> dict:
        """Long-poll payload for one deployment: replica handles plus the
        router's scoring inputs — per-replica windowed execute p95 and
        queue_est_ms (rounded to whole ms so jitter doesn't fan no-op
        updates out to every router) and the shed budget."""
        info: Dict[str, Any] = {
            "handles": state.running_replica_handles(),
            "tags": [r.tag for r in state.replicas],
            "max_concurrent_queries": state.config.max_concurrent_queries,
            "target_latency_ms": state.config.effective_target_latency_ms(),
            "p95_ms": {},
            "queue_est_ms": {},
        }
        if metrics:
            live = {r.tag for r in state.replicas}
            for tag, m in metrics.get("replicas", {}).items():
                if tag not in live:
                    continue
                info["p95_ms"][tag] = round(m.get("p95_ms", 0.0))
                info["queue_est_ms"][tag] = round(m.get("queue_est_ms", 0.0))
        return info

    def _notify_replicas(self, state: DeploymentState,
                         metrics: Optional[dict] = None) -> None:
        self._long_poll.notify_if_changed(
            _replica_key(state.name), self._membership_info(state, metrics))

    # -- queries -----------------------------------------------------------

    def get_replica_handles(self, name: str):
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                raise KeyError(f"No deployment named {name!r}")
            return self._membership_info(state)

    def get_route_table(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def list_deployments(self) -> Dict[str, dict]:
        with self._lock:
            return {n: s.status() for n, s in self._deployments.items()}

    def listen_for_change(self, keys_to_snapshot_ids: Dict[str, int]):
        # Blocks on the host's condvar; safe because the controller actor
        # runs with max_concurrency and the control loop is its own thread.
        return self._long_poll.listen_for_change(keys_to_snapshot_ids)

    # -- control loop ------------------------------------------------------

    def _control_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._run_control_loop_once()
            except Exception as e:
                logger.warning("control loop iteration failed: %s", e)
            self._shutdown.wait(self._period)

    def _run_control_loop_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
        for state in states:
            # One sensor sweep per deployment per tick: feeds the
            # autoscaler AND the router-facing membership publication.
            metrics = state.collect_metrics()
            self._autoscale(state, metrics)
            with self._lock:
                # A concurrent delete may have removed this deployment
                # between the snapshot and here; reconciling the stale
                # state would resurrect (and leak) replicas.
                if self._deployments.get(state.name) is not state:
                    continue
                state.reconcile()
                # notify_if_changed dedups, so publishing every tick only
                # fans out when membership or the rounded stats moved.
                self._notify_replicas(state, metrics)

    def _autoscale(self, state: DeploymentState,
                   metrics: Optional[dict] = None) -> None:
        cfg = state.config.autoscaling_config
        if cfg is None or state.deleting:
            return
        if metrics is None:
            metrics = state.collect_metrics()
        with self._lock:
            self._autoscale_locked(state, metrics, cfg)

    def _autoscale_locked(self, state: DeploymentState, metrics: dict,
                          cfg) -> None:
        # Scale from the TARGET count, not the live count: while a
        # scale-up is still starting replicas the live count lags, and
        # computing desired from it over-requests again every tick
        # (overshoot/oscillation).  The target already owns the in-flight
        # decision; new demand should be judged against it.
        current = max(1, state.target_replicas)
        if cfg.target_latency_ms > 0:
            # SLO mode: hold the federated windowed queue_wait+execute
            # p95 at the configured latency target.  EWMA smoothing keeps
            # one noisy tick (a single slow batch, an empty window) from
            # whipsawing the replica count.
            alpha = float(_config.get("serve_autoscale_ewma_alpha"))
            ewma_key = f"{state.name}:ewma"
            prev = self._autoscale_state.get(ewma_key)
            p95 = float(metrics.get("p95_ms", 0.0))
            smoothed = (p95 if prev is None
                        else prev + alpha * (p95 - prev))
            self._autoscale_state[ewma_key] = smoothed
            desired = cfg.desired_replicas_for_latency(smoothed, current)
        else:
            desired = cfg.desired_replicas(
                float(metrics.get("total_ongoing", 0.0)), current)
        now = time.monotonic()
        key = state.name
        if desired > state.target_replicas:
            # Upscale after upscale_delay_s of sustained demand.
            first = self._autoscale_state.setdefault(f"{key}:up", now)
            if now - first >= cfg.upscale_delay_s:
                state.set_num_replicas(desired)
                self._autoscale_state.pop(f"{key}:up", None)
            self._autoscale_state.pop(f"{key}:down", None)
        elif desired < state.target_replicas:
            first = self._autoscale_state.setdefault(f"{key}:down", now)
            if now - first >= cfg.downscale_delay_s:
                state.set_num_replicas(desired)
                self._autoscale_state.pop(f"{key}:down", None)
            self._autoscale_state.pop(f"{key}:up", None)
        else:
            self._autoscale_state.pop(f"{key}:up", None)
            self._autoscale_state.pop(f"{key}:down", None)

    def autoscale_tick(self) -> None:
        """Force one synchronous autoscale+reconcile pass (for tests)."""
        self._run_control_loop_once()

    # -- shutdown ----------------------------------------------------------

    def graceful_shutdown(self) -> None:
        self._shutdown.set()
        with self._lock:
            names = list(self._deployments)
        for name in names:
            self.delete_deployment(name)
