"""Public Serve API.

Parity with ``python/ray/serve/api.py``: ``@serve.deployment`` declares a
deployment, ``.bind()`` composes an application graph (bound deployments
passed as init args become ``DeploymentHandle``s at runtime, the
deployment-graph pattern of ``serve/deployment_graph.py``), ``serve.run``
deploys it, ``serve.start`` brings up the controller and HTTP proxy.
"""

from __future__ import annotations
import inspect
import logging

import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle

logger = logging.getLogger("ray_tpu")

_client_lock = threading.Lock()
_controller = None
_proxy = None


def start(detached: bool = True, http_host: Optional[str] = "127.0.0.1",
          http_port: int = 0):
    """Start (or connect to) the Serve control plane."""
    global _controller
    with _client_lock:
        if _controller is None:
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            try:
                _controller = ray_tpu.get_actor(CONTROLLER_NAME)
            except Exception:  # raylint: allow(swallow) no controller yet: create one below
                _controller = ray_tpu.remote(ServeController).options(
                    name=CONTROLLER_NAME, max_concurrency=64).remote()
                # Wait until the controller is live.
                ray_tpu.get(_controller.get_route_table.remote())
            from ray_tpu._private.worker import register_shutdown_hook
            register_shutdown_hook(shutdown)
        return _controller


def _get_controller():
    if _controller is None:
        return start()
    return _controller


def start_http_proxy(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start the in-process HTTP ingress; returns its base URL."""
    global _proxy
    from ray_tpu.serve._private.http_proxy import HTTPProxy
    with _client_lock:
        if _proxy is None:
            _proxy = HTTPProxy(_get_controller(), host=host, port=port)
        return _proxy.address()


class Application:
    """A bound deployment graph ready for ``serve.run``."""

    def __init__(self, root: "DeploymentNode"):
        self.root = root


class DeploymentNode:
    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _collect(self, out: List["DeploymentNode"]) -> None:
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, DeploymentNode):
                a._collect(out)
        if self not in out:
            out.append(self)


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig,
                 route_prefix: Optional[str] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config
        self.route_prefix = route_prefix

    def options(self, **updates) -> "Deployment":
        import dataclasses
        cfg_fields = {f.name for f in dataclasses.fields(DeploymentConfig)}
        cfg_updates = {k: v for k, v in updates.items() if k in cfg_fields}
        if isinstance(cfg_updates.get("autoscaling_config"), dict):
            cfg_updates["autoscaling_config"] = AutoscalingConfig(
                **cfg_updates["autoscaling_config"])
        new_cfg = dataclasses.replace(self.config, **cfg_updates)
        return Deployment(
            self.func_or_class,
            updates.get("name", self.name),
            new_cfg,
            updates.get("route_prefix", self.route_prefix))

    def bind(self, *args, **kwargs) -> DeploymentNode:
        return DeploymentNode(self, args, kwargs)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               user_config: Any = None,
               autoscaling_config: Optional[Any] = None,
               ray_actor_options: Optional[dict] = None,
               route_prefix: Optional[str] = None,
               health_check_period_s: float = 10.0,
               graceful_shutdown_timeout_s: float = 20.0,
               checkpoint: Any = None,
               max_batch_size: int = 1,
               batch_wait_timeout_s: float = 0.005,
               pad_batch_to: Optional[Any] = None,
               target_latency_ms: float = 0.0):
    """Decorator declaring a class or function as a Serve deployment.

    ``checkpoint`` accepts a ``ray_tpu.checkpoint.CheckpointRef`` (e.g.
    ``trainer_result.checkpoint.manifest_ref``): class replicas then
    cold-start with the restored pytree injected as a ``checkpoint=``
    init kwarg, loaded from the engine store on the replica itself.

    ``max_batch_size > 1`` turns each replica into an adaptive
    micro-batcher: ``__call__`` (or the deployed function) must accept a
    LIST of requests and return a list of equal length; ``pad_batch_to``
    (sorted bucket sizes) pads batches so a jitted forward never
    recompiles per batch size; ``target_latency_ms`` is the per-request
    latency budget the batcher sizes against, the router sheds over, and
    — with ``AutoscalingConfig.target_latency_ms`` — the SLO the
    autoscaler holds (0 falls back to the ``serve_target_latency_ms``
    knob).
    """

    def wrap(func_or_class):
        if checkpoint is not None and inspect.isfunction(func_or_class):
            raise ValueError(
                "@serve.deployment(checkpoint=...) requires a class: the "
                "restored pytree is injected as the replica's checkpoint= "
                "init kwarg, which a function deployment cannot receive")
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            autoscaling_config=asc,
            ray_actor_options=ray_actor_options or {},
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            checkpoint=checkpoint,
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=batch_wait_timeout_s,
            pad_batch_to=tuple(pad_batch_to) if pad_batch_to else None,
            target_latency_ms=target_latency_ms)
        return Deployment(func_or_class,
                          name or func_or_class.__name__, cfg, route_prefix)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(target, name: str = "default",
        route_prefix: Optional[str] = "/",
        ready_timeout_s: float = 300.0) -> DeploymentHandle:
    """Deploy an application (a bound deployment graph) and return a handle
    to its ingress deployment."""
    if isinstance(target, Application):
        root = target.root
    elif isinstance(target, DeploymentNode):
        root = target
    elif isinstance(target, Deployment):
        root = target.bind()
    else:
        raise TypeError(f"serve.run expects a bound deployment, got "
                        f"{type(target)}")
    controller = _get_controller()

    # Deploy dependencies first (topological from leaves), replacing bound
    # nodes in init args with DeploymentHandles.
    ordered: List[DeploymentNode] = []
    root._collect(ordered)

    def materialize(v):
        if isinstance(v, DeploymentNode):
            return DeploymentHandle(v.deployment.name, controller)
        return v

    for node in ordered:
        dep = node.deployment
        init_args = tuple(materialize(a) for a in node.args)
        init_kwargs = {k: materialize(v) for k, v in node.kwargs.items()}
        import dataclasses
        cfg_dict = dataclasses.asdict(dep.config)
        if cfg_dict.get("autoscaling_config") is not None:
            cfg_dict["autoscaling_config"] = AutoscalingConfig(
                **cfg_dict["autoscaling_config"])
        prefix = dep.route_prefix
        if node is root and prefix is None:
            prefix = route_prefix
        ray_tpu.get(controller.deploy.remote(
            dep.name, dep.func_or_class, init_args, init_kwargs,
            cfg_dict, prefix))
    # Reference semantics: serve.run blocks until the application is
    # ready — returning earlier hands out a handle whose first requests
    # race replica placement (observed on multi-process clusters, where
    # actor placement is not instantaneous).
    _wait_ready(controller, [n.deployment.name for n in ordered],
                timeout_s=ready_timeout_s)
    return DeploymentHandle(root.deployment.name, controller)


def _wait_ready(controller, names: List[str],
                timeout_s: float = 300.0) -> None:
    """Block until every deployment's replicas have ANSWERED a health
    probe (``ready_replicas``) — ``running_replicas`` counts only started
    actor handles, which are satisfied synchronously at deploy time while
    placement and __init__ still run in the background."""
    import time as _time
    deadline = _time.monotonic() + timeout_s
    pending = list(names)
    while _time.monotonic() < deadline:
        statuses = ray_tpu.get(controller.list_deployments.remote())
        pending = [n for n in names
                   if statuses.get(n, {}).get("ready_replicas", 0)
                   < statuses.get(n, {}).get("target_replicas", 1)]
        if not pending:
            return
        _time.sleep(0.1)
    raise TimeoutError(
        f"deployments not ready within {timeout_s}s: {pending}")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_controller())


def delete(name: str) -> None:
    ray_tpu.get(_get_controller().delete_deployment.remote(name))


def status() -> Dict[str, dict]:
    return ray_tpu.get(_get_controller().list_deployments.remote())


def shutdown() -> None:
    """Stop the controller (and its control-loop thread) and the proxy.
    Registered as a worker shutdown hook so a bare ray_tpu.shutdown()
    cannot leave the loop running against a dead runtime."""
    global _controller, _proxy
    from ray_tpu.serve._private.long_poll import stop_all_clients
    stop_all_clients()
    with _client_lock:
        if _proxy is not None:
            _proxy.shutdown()
            _proxy = None
        if _controller is not None:
            try:
                ray_tpu.get(_controller.graceful_shutdown.remote())
                ray_tpu.kill(_controller)
            except Exception as e:
                logger.debug("controller shutdown failed: %s", e)
            _controller = None
