"""ray_tpu.serve — model serving on actors (reference: python/ray/serve/)."""

from ray_tpu.exceptions import (BatchExecutionError,  # noqa: F401
                                ServeOverloadedError)
from ray_tpu.serve.api import (Application, Deployment,  # noqa: F401
                               delete, deployment, get_deployment_handle,
                               run, shutdown, start, start_http_proxy,
                               status)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.config import (AutoscalingConfig,  # noqa: F401
                                  DeploymentConfig)
from ray_tpu.serve.handle import (DeploymentHandle,  # noqa: F401
                                  DeploymentResponse)

__all__ = ["deployment", "run", "start", "shutdown", "delete", "status",
           "batch", "start_http_proxy", "get_deployment_handle",
           "Application", "Deployment", "DeploymentHandle",
           "DeploymentResponse", "DeploymentConfig", "AutoscalingConfig",
           "ServeOverloadedError", "BatchExecutionError"]
