"""DeploymentHandle: the Python-side entry point for calling a deployment.

Parity with ``python/ray/serve/handle.py``: ``handle.remote(...)`` routes a
request through the router (round-robin + max_concurrent_queries) and
returns a response object whose ``.result()`` blocks for the value.
``handle.method_name.remote(...)`` calls a specific method.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.serve._private.router import Router, _TrackedRef


class DeploymentResponse:
    def __init__(self, tracked: _TrackedRef):
        self._tracked = tracked

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._tracked.result(timeout)

    def ref(self):
        return self._tracked.ref()


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._remote(self._method_name, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller_handle):
        self.deployment_name = deployment_name
        self._controller = controller_handle
        self._router: Optional[Router] = None

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self._controller, self.deployment_name)
        return self._router

    def _remote(self, method_name: str, args, kwargs) -> DeploymentResponse:
        tracked = self._get_router().assign_request(method_name, args, kwargs)
        return DeploymentResponse(tracked)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._remote("__call__", args, kwargs)

    def options(self, method_name: str = "__call__") -> _MethodCaller:
        return _MethodCaller(self, method_name)

    def shutdown(self) -> None:
        """Stop the handle's router (its long-poll thread)."""
        if self._router is not None:
            self._router.shutdown()
            self._router = None

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_") or name in ("deployment_name",):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        # Handles are recreated (fresh router) on deserialization.
        return (DeploymentHandle, (self.deployment_name, self._controller))
