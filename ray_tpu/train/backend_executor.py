"""Worker-group orchestration for distributed training.

Parity with ``python/ray/train/_internal/backend_executor.py`` +
``worker_group.py``: N training workers as actors inside a placement group,
rendezvous/setup on start (the reference runs ``dist.init_process_group``,
``train/torch/config.py:54-96``; here workers join an ``xla`` collective
group and receive a device mesh), results streamed per round, failure
detection surfaced to the trainer for restart-from-checkpoint
(``backend_executor.py:461-531``).
"""

from __future__ import annotations
import logging

import os
import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

logger = logging.getLogger("ray_tpu")

_FINISHED = "__finished__"
_GROUP_SEQ = 0


@ray_tpu.remote
class RayTrainWorker:
    """One training worker (reference: ``_internal/worker_group.py:16``)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session = None
        self.thread = None

    def start_training(self, train_loop: Callable, config: Dict[str, Any],
                       checkpoint=None, group_name: Optional[str] = None,
                       dataset_shards=None, checkpoint_spec=None):
        from ray_tpu.train import session as session_mod
        mesh = None
        try:
            import jax
            from ray_tpu.collective.collective import GroupManager
            from ray_tpu.collective.collective_group.xla_process_group import (
                XLAProcessGroup)
            from ray_tpu.parallel import MeshConfig, build_mesh
            g = GroupManager.get_group(group_name) if group_name else None
            if isinstance(g, XLAProcessGroup):
                # Tensor plane spans worker PROCESSES: the session mesh is
                # the GLOBAL device mesh, and the DP gradient psum compiles
                # across hosts (the reference's per-worker process group,
                # train/torch/config.py:54-96, without the wrapper module).
                devs = jax.devices()
                mesh = build_mesh(MeshConfig(data=len(devs)), devs)
            else:
                # Each worker gets a disjoint slice of ITS HOST's devices
                # for its intra-worker mesh; the data-parallel split ACROSS
                # workers is the collective group's job. Use local devices
                # + the worker's rank among co-hosted workers (global rank
                # would misalign slices when workers span hosts).
                devs = jax.local_devices()
                hosts = max(1, jax.process_count())
                workers_per_host = max(1, -(-self.world_size // hosts))
                local_rank = self.rank % workers_per_host
                if len(devs) >= workers_per_host:
                    per = len(devs) // workers_per_host
                    local = devs[local_rank * per:(local_rank + 1) * per]
                    mesh = build_mesh(MeshConfig(data=len(local)), local)
        except Exception as e:
            logger.debug("mesh detection failed; no local mesh: %s", e)
            mesh = None
        self.session = session_mod._init_session(
            world_rank=self.rank, world_size=self.world_size,
            checkpoint=checkpoint, mesh=mesh, config=config,
            collective_group_name=group_name,
            dataset_shards=dataset_shards, checkpoint_spec=checkpoint_spec)
        sess = self.session
        # Collective groups and task context are thread-local; hand the actor
        # thread's bindings to the training-loop thread.
        from ray_tpu._private.runtime import task_context
        from ray_tpu.collective.collective import GroupManager, _local_groups
        groups = GroupManager._groups()
        ctx = (task_context.node_id, task_context.actor_id,
               task_context.job_id, task_context.devices)

        def _run():
            from ray_tpu.train import session as sm
            sm._session.s = sess  # bind session into the loop thread
            _local_groups.groups = groups
            (task_context.node_id, task_context.actor_id,
             task_context.job_id, task_context.devices) = ctx
            try:
                train_loop(config)
            except BaseException as e:  # noqa: BLE001
                sess.error = e
            finally:
                # Drain in-flight engine saves BEFORE the completion
                # sentinel: a result consumer must observe the last
                # checkpoint as committed, not queued.
                try:
                    sess._close_engine(had_error=sess.error is not None)
                except Exception as ce:
                    logger.warning("checkpoint engine close failed: %s", ce)
                sess.finished.set()
                sess.results.put(_FINISHED)

        self.thread = threading.Thread(target=_run, daemon=True)
        self.thread.start()
        return self.rank

    def next_result(self, timeout: Optional[float] = None):
        """Block until the next reported result (or completion sentinel).

        ``timeout=None`` blocks indefinitely: a slow epoch is not a failure.
        Worker death is still detected (the actor call raises), and the loop
        thread's completion sentinel always arrives via ``finally``.
        """
        import queue as _q
        try:
            item = self.session.results.get(timeout=timeout)
        except _q.Empty:
            raise TimeoutError(f"worker {self.rank} produced no result "
                               f"within {timeout}s")
        if item == _FINISHED:
            if self.session.error is not None:
                raise self.session.error
            return _FINISHED
        return item

    def get_final_checkpoint(self):
        return self.session.latest_checkpoint if self.session else None

    def ping(self):
        return "ok"


class BackendExecutor:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 collective_backend: Optional[str] = None,
                 results_timeout_s: Optional[float] = None):
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.collective_backend = collective_backend
        self.results_timeout_s = results_timeout_s
        self.pg = None
        self.workers: List[Any] = []
        self.group_name: Optional[str] = None
        self._finished: set = set()

    def start(self):
        bundles = [dict(self.resources_per_worker)
                   for _ in range(self.num_workers)]
        self.pg = placement_group(bundles, strategy=self.placement_strategy)
        if not self.pg.wait(60):
            raise exc.PlacementGroupSchedulingError(
                f"could not place {self.num_workers} train workers with "
                f"{self.resources_per_worker} each")
        num_cpus = self.resources_per_worker.get("CPU", 1)
        num_tpus = self.resources_per_worker.get("TPU", 0)
        self.workers = [
            RayTrainWorker.options(
                num_cpus=num_cpus, num_tpus=num_tpus,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg,
                    placement_group_bundle_index=i),
            ).remote(i, self.num_workers)
            for i in range(self.num_workers)
        ]
        ray_tpu.get([w.ping.remote() for w in self.workers])
        if self.collective_backend:
            from ray_tpu.collective import create_collective_group
            global _GROUP_SEQ
            _GROUP_SEQ += 1
            # Unique per attempt AND per process lifetime: a recycled
            # id(self) must never alias a previous attempt's tensor-plane
            # rendezvous keys.
            self.group_name = f"train_{os.getpid()}_{_GROUP_SEQ}"
            create_collective_group(
                self.workers, self.num_workers,
                list(range(self.num_workers)),
                backend=self.collective_backend, group_name=self.group_name)

    def start_training(self, train_loop: Callable, config: Dict[str, Any],
                       checkpoint=None, dataset_shards=None,
                       checkpoint_spec=None):
        self._finished = set()
        ray_tpu.get([
            w.start_training.remote(
                train_loop, config, checkpoint, self.group_name,
                dataset_shards[i] if dataset_shards else None,
                checkpoint_spec)
            for i, w in enumerate(self.workers)])

    def get_next_results(self, timeout: Optional[float] = None):
        """One result per still-running worker, or None once all finished.

        Workers that already hit their completion sentinel are not polled
        again (a worker reporting fewer rounds than its peers must not hang
        the round). Raises the training error (or ActorDiedError) for failed
        workers — callers use that signal for restart handling.
        """
        live = [(i, w) for i, w in enumerate(self.workers)
                if i not in self._finished]
        if not live:
            return None
        timeout = timeout if timeout is not None else self.results_timeout_s
        refs = [w.next_result.remote(timeout) for _, w in live]
        results = ray_tpu.get(
            refs, timeout=None if timeout is None else timeout + 30)
        out = []
        for (i, _), r in zip(live, results):
            if r == _FINISHED:
                self._finished.add(i)
            else:
                out.append(r)
        if not out and len(self._finished) == len(self.workers):
            return None
        return out

    def get_final_checkpoints(self):
        """Final checkpoint per worker, None for workers that are dead or
        miss their deadline — one crashed worker must not hang shutdown."""
        from ray_tpu._private.backoff import BackoffPolicy
        from ray_tpu._private.config import _config
        policy = BackoffPolicy(
            deadline_s=float(_config.checkpoint_final_timeout_s))
        out = []
        for i, w in enumerate(self.workers):
            state = policy.start()
            try:
                out.append(ray_tpu.get(w.get_final_checkpoint.remote(),
                                       timeout=state.attempt_timeout()))
            except Exception as e:
                logger.warning(
                    "final checkpoint from worker %d unavailable (%s: %s); "
                    "returning partial results", i, type(e).__name__, e)
                out.append(None)
        return out

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception as e:
                logger.debug("worker kill failed: %s", e)
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception as e:
                logger.debug("placement group removal failed: %s", e)
        self.workers = []
