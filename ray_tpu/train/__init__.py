from ray_tpu.train import session
from ray_tpu.train.backend_executor import BackendExecutor, RayTrainWorker
from ray_tpu.train.step import make_lm_train_step
from ray_tpu.train.trainer import JaxTrainer

__all__ = ["JaxTrainer", "BackendExecutor", "RayTrainWorker", "session",
           "make_lm_train_step"]
