"""Sharded training-step builder: one function from (model, mesh, rules) to a
compiled SPMD train step with DP/FSDP/TP/SP/PP composed as mesh axes.

This is the compute-plane heart of the Train layer (the reference's
equivalent moment is DDP wrapping in ``train/torch/train_loop_utils.py:49``
— here the "wrap" is sharding annotations + XLA collectives, and pipeline
stages replace none-existent reference PP, SURVEY §2.5).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import transformer
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.observability import goodput
from ray_tpu.parallel import (ShardingRules, batch_sharding, pipeline_apply,
                              shard_pytree)


def make_lm_train_step(cfg: TransformerConfig, mesh: Mesh,
                       rules: Optional[ShardingRules] = None,
                       optimizer: Optional[optax.GradientTransformation] = None,
                       num_microbatches: int = 4):
    """Build (init_fn, step_fn) for language-model training on ``mesh``.

    - pipe axis > 1: transformer blocks run under the GPipe schedule
      (``pipeline_apply``); embed/head compute on every stage (cheap).
    - seq axis > 1: attention inside blocks uses ring attention.
    - fsdp/tensor axes shard params per ``transformer.logical_axes``.
    - data (+fsdp) shards the batch; XLA inserts the gradient psum.

    step_fn(state, tokens) -> (state, metrics); state = (params, opt_state).
    """
    rules = rules or ShardingRules()
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    pipe = mesh.shape.get("pipe", 1)
    if pipe > 1:
        if cfg.n_layers % pipe != 0:
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                             f"pipe={pipe}")
        # Stage-shard the stacked layer dim so each stage holds only its
        # layers' params.
        rules = rules.with_overrides(layers="pipe")

    def loss_fn(params, tokens):
        if pipe == 1:
            return transformer.loss_fn(params, tokens, cfg, mesh)
        # Pipeline path: embed -> pipelined blocks -> head.
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        x = params["embed"].astype(cfg.dtype)[inputs]
        layers_per_stage = cfg.n_layers // pipe

        def stage_fn(stage_params, h):
            B, L, _ = h.shape
            positions = jnp.broadcast_to(jnp.arange(L)[None], (B, L))
            block = functools.partial(transformer._block, cfg=cfg, mesh=mesh)
            if cfg.remat:
                block = jax.checkpoint(block)

            def body(h, layer_params):
                return block(layer_params, h, positions), None

            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        # blocks leaves: [n_layers, ...] -> [pipe, layers_per_stage, ...]
        stage_params = jax.tree.map(
            lambda p: p.reshape((pipe, layers_per_stage) + p.shape[1:]),
            params["blocks"])
        x = pipeline_apply(stage_fn, stage_params, x, mesh,
                           num_microbatches=num_microbatches)
        return transformer.head_and_loss(params, x, targets, cfg)

    def init_fn(key) -> Tuple[Any, Any]:
        params = transformer.init_params(key, cfg)
        axes = transformer.logical_axes(cfg)
        params = shard_pytree(params, axes, mesh, rules)
        opt_state = optimizer.init(params)
        return params, opt_state

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, tokens):
        params, opt_state = state
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return (params, opt_state), {"loss": loss, "grad_norm": gnorm}

    def shard_batch(tokens):
        return jax.device_put(tokens, batch_sharding(mesh, rules, ndim=2))

    # Goodput compile detection: the first call per (state, tokens)
    # signature traces+compiles the whole step — pipeline stages, ring
    # attention and the gradient psum included, since parallel/ runs
    # inline under this jit — and lands in the ledger's ``compile``
    # category; a new tokens shape mid-run is a recompile (runtime
    # mirror of lint rule R21).
    return init_fn, goodput.instrument_jit(step_fn, name="train.step_fn"), \
        shard_batch
