"""JaxTrainer — the DataParallelTrainer equivalent.

Parity with ``python/ray/train/data_parallel_trainer.py:50`` +
``base_trainer.py:327``: ``fit()`` spins up a worker group in a placement
group, runs ``train_loop_per_worker`` on every worker, streams
``session.report`` rounds, and on worker failure restarts the group from the
latest checkpoint up to ``FailureConfig.max_failures``
(``backend_executor.py:461-531``). TPU-native: workers pin to TPU hosts;
inside the loop the user gets a mesh (``session.get_mesh``) and an optional
``xla`` collective group instead of a torch process group.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private.backoff import BackoffPolicy
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (FailureConfig, Result, RunConfig,
                                ScalingConfig)
from ray_tpu.observability import goodput
from ray_tpu.train.backend_executor import BackendExecutor

logger = logging.getLogger("ray_tpu")


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable[[Dict[str, Any]], None],
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 collective_backend: Optional[str] = "xla",
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 results_timeout_s: Optional[float] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._collective_backend = collective_backend
        self._resume_from = resume_from_checkpoint
        self._results_timeout_s = results_timeout_s
        # name -> ray_tpu.data.Dataset; each is streaming_split across the
        # worker group and handed out via session.get_dataset_shard
        # (reference: DataParallelTrainer datasets= + DataConfig)
        self._datasets = datasets or {}
        # Largest observed elastic-restart downtime (s); checkpoint specs
        # carry it so the "auto" cadence solver prices failures correctly.
        self._restart_cost_s = 0.0

    def _dataset_shards(self):
        if not self._datasets:
            return None
        n = self.scaling_config.num_workers
        shard_sets: list = [{} for _ in range(n)]
        for name, ds in self._datasets.items():
            for i, it in enumerate(ds.streaming_split(n, equal=True)):
                shard_sets[i][name] = it
        return shard_sets

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        failures = 0
        restart_backoff = BackoffPolicy(base_s=0.1, max_s=2.0, deadline_s=0)
        max_failures = self.run_config.failure_config.max_failures
        checkpoint = self._resume_from
        history = []
        last_metrics: Dict[str, Any] = {}
        engine_root = self._engine_root()
        # Goodput: stamp of the failure that triggered the current restart
        # attempt; the gap until training is running again is the job's
        # elastic-restart downtime, attributed on the driver ledger.
        restart_t0: Optional[float] = None
        while True:
            executor = BackendExecutor(
                self.scaling_config.num_workers,
                self.scaling_config.worker_resources(),
                self.scaling_config.placement_strategy,
                self._collective_backend,
                results_timeout_s=self._results_timeout_s)
            try:
                executor.start()
                executor.start_training(self._train_loop, self._config,
                                        checkpoint,
                                        dataset_shards=self._dataset_shards(),
                                        checkpoint_spec=self._checkpoint_spec(
                                            engine_root))
                if restart_t0 is not None:
                    dt = time.monotonic() - restart_t0
                    # Feeds the "auto" cadence solver on the NEXT spec:
                    # a failure costs its restart, so pricier restarts
                    # shift the optimum toward denser checkpoints.
                    self._restart_cost_s = max(self._restart_cost_s, dt)
                    if goodput.ENABLED:
                        goodput.account("restart_downtime", dt)
                    restart_t0 = None
                while True:
                    round_results = executor.get_next_results()
                    if round_results is None:
                        break
                    for r in round_results:
                        history.append(r["metrics"])
                        if r["checkpoint"] is not None and r["rank"] == 0:
                            checkpoint = r["checkpoint"]
                    if round_results:
                        last_metrics = round_results[0]["metrics"]
                finals = executor.get_final_checkpoints()
                if finals and finals[0] is not None:
                    checkpoint = finals[0]
                return Result(metrics=last_metrics, checkpoint=checkpoint,
                              metrics_history=history)
            except (exc.ActorDiedError, exc.NodeDiedError,
                    exc.TaskError) as e:
                if restart_t0 is None:
                    restart_t0 = time.monotonic()
                failures += 1
                if max_failures != -1 and failures > max_failures:
                    return Result(metrics=last_metrics, checkpoint=checkpoint,
                                  error=e, metrics_history=history)
                # Elastic restart from the last *committed* manifest when the
                # engine is on (reference: backend_executor.py:510-531). The
                # streamed in-memory checkpoint is the fallback — it may be
                # ahead of the last commit, but it dies with the driver.
                committed = self._committed_checkpoint(engine_root)
                if committed is not None:
                    checkpoint = committed
                time.sleep(restart_backoff.delay_for(failures - 1))
                continue
            finally:
                # Never leak the worker group / placement group, whatever
                # path exits the attempt.
                executor.shutdown()

    def _engine_root(self) -> Optional[str]:
        """Engine store under <storage_path>/<name>/checkpoints; None keeps
        checkpoints driver-memory-only (small runs, existing behavior)."""
        storage = self.run_config.storage_path
        if not storage:
            return None
        name = self.run_config.name or "experiment"
        return os.path.join(storage, name, "checkpoints")

    def _checkpoint_spec(self, engine_root: Optional[str]):
        if engine_root is None:
            return None
        cfg = self.run_config.checkpoint_config
        # run_token namespaces pending/ save keys per attempt, so shard
        # indexes left by a crashed attempt can never join a new commit.
        # base_step carries the step counter across attempts: a restarted
        # session resumes numbering AFTER the last committed manifest, so
        # retention (which keeps the newest commits) and the LATEST
        # fallback scan see one monotonic step sequence instead of a
        # post-crash counter reset shadowed by stale pre-crash manifests.
        # frequency passes through verbatim — an int cadence, or "auto"
        # for the risk-tuned Young–Daly solver (checkpoint/cadence.py);
        # restart_cost_s feeds that solver's failure pricing.
        return {"root": engine_root,
                "num_to_keep": cfg.num_to_keep,
                "frequency": cfg.checkpoint_frequency,
                "base_step": self._committed_step(engine_root),
                "run_token": uuid.uuid4().hex[:8],
                "restart_cost_s": self._restart_cost_s}

    def _committed_step(self, engine_root: str) -> int:
        from ray_tpu.checkpoint import (CheckpointError, read_manifest,
                                        resolve_latest)
        try:
            name = resolve_latest(engine_root)
            if name is None:
                return 0
            return int(read_manifest(engine_root, name).step)
        except CheckpointError as e:
            logger.warning("could not read last committed step (restarting "
                           "the counter from 0): %s", e)
            return 0

    def _committed_checkpoint(self, engine_root: Optional[str]):
        if engine_root is None:
            return None
        from ray_tpu.checkpoint import resolve_latest
        name = resolve_latest(engine_root)
        if name is None:
            return None
        logger.info("restarting from committed checkpoint manifest %s", name)
        return Checkpoint.from_manifest(engine_root, name)
