"""Per-worker training session.

Parity with ``python/ray/air/session.py`` + ``train/_internal/session.py:261``:
``report(metrics, checkpoint=...)`` streams results to the driver;
``get_checkpoint`` hands back the restore point; rank/world accessors mirror
the reference's. The TPU additions: ``get_mesh()`` exposes the worker's
device mesh, and reported checkpoints may hold device arrays (they stay
resident; the store keeps descriptors).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.observability import goodput, perf

logger = logging.getLogger("ray_tpu")


class _TrainSession:
    def __init__(self, world_rank: int, world_size: int,
                 local_rank: int = 0,
                 checkpoint=None, mesh=None, config=None,
                 collective_group_name: Optional[str] = None,
                 dataset_shards=None, checkpoint_spec=None):
        self.dataset_shards = dataset_shards or {}
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.collective_group_name = collective_group_name
        self.results: "queue.Queue" = queue.Queue()
        self.checkpoint = checkpoint
        self.mesh = mesh
        self.config = config or {}
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.latest_checkpoint = checkpoint
        # Engine-backed persistence (trainer passes a spec when
        # RunConfig.storage_path is set): every reported checkpoint is also
        # snapshotted asynchronously through ray_tpu.checkpoint, so the
        # driver restarts from a committed manifest, not a driver-memory blob.
        self.checkpoint_spec = checkpoint_spec
        self.checkpoint_engine = None
        # Resume step numbering after the last committed manifest
        # (spec["base_step"], carried across elastic restarts by the
        # trainer) — a counter that restarted at 0 would write manifests
        # that sort BELOW the stale pre-crash ones, and retention would
        # reap the fresh commits instead of the stale ones.
        self._ckpt_seq = int((checkpoint_spec or {}).get("base_step") or 0)
        # Perf plane: monotonic stamp of the previous report(), so the
        # inter-report interval — the user's step wall time — lands in
        # the train.step histogram.
        self._last_report_s = 0.0
        # Risk-tuned cadence (checkpoint_frequency="auto"): the solver
        # needs measured step/ckpt costs, so the session keeps its own
        # report stamp (perf.ENABLED may be off) and gates engine saves
        # on seq distance — a modulo check breaks when the interval is
        # re-solved mid-run.
        self._cadence = None
        self._last_saved_seq: Optional[int] = None
        self._cadence_stamp_s = 0.0
        if (checkpoint_spec or {}).get("frequency") == "auto":
            from ray_tpu.checkpoint import CadenceController
            self._cadence = CadenceController(
                restart_cost_s=float(
                    checkpoint_spec.get("restart_cost_s") or 0.0))

    def _engine(self):
        if self.checkpoint_engine is None and self.checkpoint_spec:
            from ray_tpu.checkpoint import CheckpointEngine
            self.checkpoint_engine = CheckpointEngine(
                self.checkpoint_spec["root"],
                num_to_keep=self.checkpoint_spec.get("num_to_keep"))
        return self.checkpoint_engine

    def _engine_save(self, checkpoint) -> None:
        """Async engine snapshot of a reported checkpoint. The report call
        returns once the device->host copy is queued; commit happens on the
        engine's writer thread."""
        self._ckpt_seq += 1
        if self._cadence is not None:
            # Auto cadence: save when the re-solved interval has elapsed
            # since the last save (the first reported checkpoint always
            # anchors — restore needs an early committed manifest).
            interval = self._cadence.interval_steps()
            if (self._last_saved_seq is not None
                    and self._ckpt_seq - self._last_saved_seq < interval):
                return
        else:
            freq = max(1, int(self.checkpoint_spec.get("frequency") or 1))
            if (self._ckpt_seq - 1) % freq != 0:
                return
        self._last_saved_seq = self._ckpt_seq
        tree = checkpoint.to_dict() if hasattr(checkpoint, "to_dict") \
            else checkpoint
        token = self.checkpoint_spec.get("run_token", "run")
        t0 = time.monotonic() if self._cadence is not None else 0.0
        self._engine().save(
            tree, step=self._ckpt_seq, rank=self.world_rank,
            world_size=self.world_size,
            save_key=f"{token}-{self._ckpt_seq:08d}")
        if self._cadence is not None:
            self._cadence.observe_ckpt(time.monotonic() - t0)

    def _close_engine(self, had_error: bool) -> None:
        eng = self.checkpoint_engine
        if eng is None:
            return
        if had_error:
            # A crashed loop must not stall shutdown behind a commit that
            # waits on dead peers; committed manifests are already durable.
            eng.flush(timeout=0.5)
        else:
            if not eng.flush(timeout=60.0):
                logger.warning("checkpoint: in-flight save unfinished at "
                               "session close (rank %d)", self.world_rank)
            eng.close(timeout=1.0)


_session = threading.local()


def _init_session(**kwargs) -> _TrainSession:
    _session.s = _TrainSession(**kwargs)
    return _session.s


def _get_session() -> Optional[_TrainSession]:
    return getattr(_session, "s", None)


def _shutdown_session():
    _session.s = None


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Stream a result row (and optionally a checkpoint) to the driver.

    Perf-plane breakdown per report: ``train.step`` (wall time since the
    previous report — the user's step loop), ``train.ckpt_enqueue`` (the
    synchronous share of the engine save: device->host copy + queueing;
    hash/write/commit stay on the writer thread), and ``train.report``
    (this call's own cost).

    Goodput ledger: each report closes one step — wall time since the
    previous mark that no explicit interval claimed (data_wait,
    collective_wait, ckpt_stall, compile are accounted at their own
    sites) is credited to ``compute`` via :func:`goodput.step_mark`."""
    s = _get_session()
    if s is None:
        raise RuntimeError("session.report() called outside a train worker")
    t0 = time.monotonic() if perf.ENABLED else 0.0
    if t0 and s._last_report_s:
        perf.observe("train.step", (t0 - s._last_report_s) * 1e3)
    if s._cadence is not None:
        now_c = time.monotonic()
        if s._cadence_stamp_s:
            s._cadence.observe_step(now_c - s._cadence_stamp_s)
        s._cadence_stamp_s = now_c
    if goodput.ENABLED:
        goodput.step_mark()
    if checkpoint is not None:
        s.latest_checkpoint = checkpoint
        if s.checkpoint_spec:
            s._engine_save(checkpoint)
            if t0:
                perf.observe("train.ckpt_enqueue",
                             (time.monotonic() - t0) * 1e3)
    s.results.put({"metrics": dict(metrics), "checkpoint": checkpoint,
                   "rank": s.world_rank})
    if t0:
        now = time.monotonic()
        s._last_report_s = now
        perf.observe("train.report", (now - t0) * 1e3)


def get_checkpoint():
    s = _get_session()
    return s.checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    """This worker's split of ``JaxTrainer(datasets={name: ds})`` — a
    ``DataIterator`` (reference ``session.get_dataset_shard``)."""
    s = _get_session()
    if s is None or name not in s.dataset_shards:
        return None
    return s.dataset_shards[name]


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_mesh():
    """The jax device mesh assigned to this worker group (TPU-native)."""
    s = _get_session()
    return s.mesh if s else None


def get_config() -> Dict[str, Any]:
    s = _get_session()
    return dict(s.config) if s else {}


def get_collective_group_name() -> Optional[str]:
    """Name of the collective group the executor created for this worker
    group (None when the trainer was built with collective_backend=None)."""
    s = _get_session()
    return s.collective_group_name if s else None


def shard_batch(array, spec=None):
    """Place this worker's LOCAL batch across the session mesh's
    data-parallel axes as one global array. On a process-spanning mesh
    (multi-host tensor plane) each worker contributes its shard
    (``jax.make_array_from_process_local_data``); single-process meshes
    just device_put with the sharding. The returned array feeds a pjit'd
    step whose gradient psum then rides the compiled collectives.

    The default spec comes from the ``batch`` entry of the rules table
    (``("data", "fsdp")``), matching what ``train.step.batch_sharding``
    pins on the jitted step — a bare ``P("data")`` here would make XLA
    reshard the batch over fsdp at the step boundary on every call.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from ray_tpu.parallel.sharding import ShardingRules
    s = _get_session()
    if s is None or s.mesh is None:
        raise RuntimeError("shard_batch() needs a session with a mesh")
    arr = np.asarray(array)
    if spec is None:
        spec = ShardingRules().sharding(
            s.mesh, ("batch",) + (None,) * (max(1, arr.ndim) - 1)).spec
    sharding = NamedSharding(s.mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, arr)
    return jax.device_put(arr, sharding)
