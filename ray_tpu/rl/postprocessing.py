"""Advantage estimation.

Parity with ``rllib/evaluation/postprocessing.py`` (``compute_advantages``,
``compute_gae_for_sample_batch``): GAE(lambda) over collected fragments,
with value bootstrapping at truncation boundaries.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


def compute_gae(batch: SampleBatch, last_value: float, gamma: float = 0.99,
                lambda_: float = 0.95,
                standardize_advantages: bool = False) -> SampleBatch:
    """Append ADVANTAGES and VALUE_TARGETS to ``batch`` (in place).

    ``terminateds`` zero the bootstrap (true episode end); ``truncateds``
    bootstrap from VF_PREDS of the *terminal* obs which the rollout worker
    stores as the step's own vf estimate continuation — we bootstrap from
    ``last_value`` only past the fragment end.
    """
    rewards = batch[SampleBatch.REWARDS].astype(np.float64)
    values = batch[SampleBatch.VF_PREDS].astype(np.float64)
    terminated = batch[SampleBatch.TERMINATEDS].astype(bool)
    truncated = batch.get(SampleBatch.TRUNCATEDS)
    truncated = (truncated.astype(bool) if truncated is not None
                 else np.zeros_like(terminated))
    bootstrap = batch.get("bootstrap_values")
    n = len(rewards)
    adv = np.zeros(n, np.float64)
    last_gae = 0.0
    for t in reversed(range(n)):
        if t == n - 1:
            if truncated[t] and bootstrap is not None:
                next_value = float(bootstrap[t])
            elif terminated[t]:
                next_value = 0.0
            else:
                next_value = last_value
        elif terminated[t] or truncated[t]:
            next_value = 0.0
        else:
            next_value = values[t + 1]
        # At episode boundaries inside the fragment the next state belongs
        # to a new episode: cut the recursion. For truncation, bootstrap
        # from the recorded terminal-state value if available.
        if t < n - 1 and truncated[t] and bootstrap is not None:
            next_value = float(bootstrap[t])
        nonterminal = 0.0 if terminated[t] else 1.0
        boundary = terminated[t] or truncated[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lambda_ * (0.0 if boundary else last_gae)
        adv[t] = last_gae
    targets = adv + values
    if standardize_advantages:
        adv = (adv - adv.mean()) / max(1e-4, adv.std())
    batch[SampleBatch.ADVANTAGES] = adv.astype(np.float32)
    batch[SampleBatch.VALUE_TARGETS] = targets.astype(np.float32)
    return batch


def standardize(x: np.ndarray) -> np.ndarray:
    """Reference: ``rllib/utils/numpy.py`` ``standardized`` (ppo.py:415)."""
    return (x - x.mean()) / max(1e-4, x.std())


def add_next_obs(batch: SampleBatch) -> SampleBatch:
    """Append NEXT_OBS from the obs column + episode boundaries, dropping
    fragment-boundary rows whose successor obs never made it into the
    fragment (standard discard; negligible at fragment_length >= 4).

    Shared by the replay-based learners (DQN/SAC): within an episode
    s'[t] = s[t+1]; at a non-terminal fragment/episode boundary the
    transition is dropped rather than paired with a bogus successor.
    """
    eps = batch[SampleBatch.EPS_ID]
    keep = np.ones(len(batch), bool)
    # zeros (not empty): rows at masked boundaries still pass through the
    # target net, and garbage floats there can overflow to inf and poison
    # 0 * inf = NaN targets.
    next_obs = np.zeros_like(batch[SampleBatch.OBS])
    next_obs[:-1] = batch[SampleBatch.OBS][1:]
    for t in range(len(batch)):
        last = t == len(batch) - 1 or eps[t + 1] != eps[t]
        if last and not batch[SampleBatch.TERMINATEDS][t]:
            keep[t] = False
    out = SampleBatch({**{k: v for k, v in batch.items()},
                       SampleBatch.NEXT_OBS: next_obs})
    idx = np.nonzero(keep)[0]
    return SampleBatch({k: v[idx] for k, v in out.items()})
