"""Agent/action connectors: pluggable obs/action transform pipelines.

Parity with ``rllib/connectors/`` (``connectors/__init__.py:1``,
``agent/obs_preproc.py``, ``action/clip.py`` roles): small composable
transforms that sit between the environment and the policy —
observation preprocessing on the way IN (flatten, running-stat
normalization, frame stacking, clipping) and action postprocessing on
the way OUT (clip/unsquash to the action space). Connectors carry their
own state (e.g. normalization statistics) and serialize with the policy
weights so restored policies see identically-transformed inputs.

Wiring: ``model={"obs_connectors": [...], "action_connectors": [...]}``
on any algorithm config — the RolloutWorker applies them around
``compute_actions``; states ride ``get_weights``/``set_weights``.
Connectors are constructed per worker from (name, kwargs) specs so they
cross process boundaries without pickling live state.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.rl.env import Box

_REGISTRY: Dict[str, type] = {}


def register_connector(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.name = name
        return cls
    return deco


def build_connectors(specs: Optional[Sequence]) -> List["Connector"]:
    """specs: list of name | (name, kwargs) | Connector instances."""
    out: List[Connector] = []
    for spec in specs or ():
        if isinstance(spec, Connector):
            out.append(spec)
        elif isinstance(spec, str):
            out.append(_REGISTRY[spec]())
        else:
            name, kwargs = spec
            out.append(_REGISTRY[name](**dict(kwargs)))
    return out


class Connector:
    """One transform. ``__call__`` maps a BATCH (obs [B, ...] or actions
    [B, ...]); ``on_episode_end(env_indices)`` resets per-env state."""

    name = "connector"

    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def peek(self, x: np.ndarray) -> np.ndarray:
        """Transform without advancing any internal state (bootstrap
        side-looks). Stateless connectors: same as __call__."""
        return self(x)

    def on_episode_end(self, env_indices) -> None:
        pass

    def state(self) -> Any:
        return None

    def set_state(self, state: Any) -> None:
        pass


@register_connector("flatten_obs")
class FlattenObs(Connector):
    """[B, *dims] -> [B, prod(dims)] (obs_preproc flatten role)."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


@register_connector("clip_obs")
class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(np.asarray(obs), self.low, self.high)


@register_connector("normalize_obs")
class NormalizeObs(Connector):
    """Running mean/std normalization (MeanStdFilter role). The running
    statistics ARE policy state: they serialize with the weights."""

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self._n = 1e-4
        self._sum: Optional[np.ndarray] = None
        self._sq: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        flat = obs.reshape(obs.shape[0], -1)
        if self._sum is None:
            self._sum = np.zeros(flat.shape[1])
            self._sq = np.zeros(flat.shape[1])
        if self.update:
            self._n += flat.shape[0]
            self._sum += flat.sum(0)
            self._sq += (flat ** 2).sum(0)
        mean = self._sum / self._n
        var = np.maximum(self._sq / self._n - mean ** 2, 1e-8)
        out = (flat - mean) / np.sqrt(var)
        return np.clip(out, -self.clip, self.clip).reshape(
            obs.shape).astype(np.float32)

    def peek(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._sum is None:
            return obs.astype(np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        mean = self._sum / self._n
        var = np.maximum(self._sq / self._n - mean ** 2, 1e-8)
        out = np.clip((flat - mean) / np.sqrt(var), -self.clip, self.clip)
        return out.reshape(obs.shape).astype(np.float32)

    def state(self):
        return (self._n, self._sum, self._sq)

    def set_state(self, state):
        if state is not None:
            self._n, self._sum, self._sq = state


@register_connector("frame_stack")
class FrameStack(Connector):
    """Concatenate the last k observations per sub-env along the feature
    axis (the velocity-from-positions trick; per-env ring buffer reset
    at episode ends)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._buf: Optional[np.ndarray] = None  # [B, k, D]

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self._buf is None or len(self._buf) != len(flat):
            self._buf = np.repeat(flat[:, None], self.k, axis=1)
        else:
            self._buf = np.concatenate(
                [self._buf[:, 1:], flat[:, None]], axis=1)
        return self._buf.reshape(len(flat), -1)

    def peek(self, obs):
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(obs.shape[0], -1)
        if self._buf is None or len(self._buf) != len(flat):
            return np.repeat(flat[:, None], self.k, axis=1).reshape(
                len(flat), -1)
        shifted = np.concatenate([self._buf[:, 1:], flat[:, None]], axis=1)
        return shifted.reshape(len(flat), -1)

    def on_episode_end(self, env_indices):
        if self._buf is not None:
            idx = np.asarray(env_indices, int)
            # next __call__ overwrites all k slots with the reset obs
            self._buf[idx] = 0.0

    def state(self):
        return None  # rollout-transient; fragments replay raw obs


@register_connector("clip_actions")
class ClipActions(Connector):
    """Clip continuous actions into the Box (action/clip.py role)."""

    def __init__(self, low=None, high=None):
        self.low, self.high = low, high

    def bind_space(self, space):
        if isinstance(space, Box) and self.low is None:
            self.low = np.asarray(space.low)
            self.high = np.asarray(space.high)

    def __call__(self, actions):
        if self.low is None:
            return actions
        return np.clip(np.asarray(actions), self.low, self.high)


@register_connector("scale_actions")
class ScaleActions(Connector):
    """Map [-1, 1] policy outputs onto the Box (unsquash role)."""

    def __init__(self):
        self._scale = self._center = None

    def bind_space(self, space):
        if isinstance(space, Box):
            lo = np.asarray(space.low, np.float32)
            hi = np.asarray(space.high, np.float32)
            self._scale = (hi - lo) / 2.0
            self._center = (hi + lo) / 2.0

    def __call__(self, actions):
        if self._scale is None:
            return actions
        return np.asarray(actions) * self._scale + self._center


class ConnectorPipeline(Connector):
    """Ordered composition; state is the tuple of member states."""

    def __init__(self, connectors: Sequence[Connector]):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def peek(self, x):
        for c in self.connectors:
            x = c.peek(x)
        return x

    def on_episode_end(self, env_indices):
        for c in self.connectors:
            c.on_episode_end(env_indices)

    def bind_space(self, space):
        for c in self.connectors:
            if hasattr(c, "bind_space"):
                c.bind_space(space)

    def state(self) -> Tuple:
        return tuple(c.state() for c in self.connectors)

    def set_state(self, state):
        if state:
            for c, s in zip(self.connectors, state):
                c.set_state(s)
