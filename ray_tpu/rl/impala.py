"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Parity with ``rllib/algorithms/impala/`` (async sampling into a central
learner, ``vtrace_torch.py``). The reference's ``MultiGPULearnerThread`` +
loader threads (``multi_gpu_learner_thread.py:20-46``) become: in-flight
``sample.remote()`` futures kept saturated per worker, and ONE jitted
V-trace update the batch enters with a single device transfer — the
"loader thread" is ``jax.device_put``'s async dispatch.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples


class ImpalaConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or Impala)
        self.lr = 5e-4
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.num_rollout_workers = 2
        self.rollout_fragment_length = 50
        self.max_sample_requests_in_flight_per_worker = 2
        self.broadcast_interval = 1
        # None = vanilla V-trace PG; a float enables APPO's clipped
        # surrogate (declared here so .training(clip_param=) binds
        # instead of falling into the extras dict)
        self.clip_param = None


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value,
           discounts, clip_rho: float = 1.0, clip_c: float = 1.0):
    """V-trace targets (Espeholt et al. 2018), time-major [T, B] inputs.

    Returns (vs, pg_advantages). Pure function; used under jit.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(clip_c, rhos)
    values_t_plus_1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + discounts * values_t_plus_1 - values)

    def backward(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values
    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * vs_t_plus_1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    """V-trace learner. ``clip_param`` switches the policy term from the
    vanilla V-trace PG estimator to APPO's clipped surrogate over the
    same V-trace advantages (``rllib/algorithms/appo``)."""

    def __init__(self, init_params, cfg: ImpalaConfig, continuous: bool,
                 clip_param: float = None):
        self.cfg = cfg
        from ray_tpu.rl.recurrent import uses_memory_model
        model_cfg = dict(cfg.model)
        recurrent = uses_memory_model(model_cfg)
        if recurrent:
            # The classic IMPALA rmsprop(eps=0.1) effectively multiplies
            # small gradients by ~1/eps — tuned for its large fcnet, it
            # destabilizes the gated-recurrence gradients (measured:
            # CartPole pinned at random under rmsprop, learns under
            # adam). Memory models get adam, like the reference's
            # recurrent tuned examples.
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(cfg.grad_clip),
                optax.adam(cfg.lr))
        else:
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(cfg.grad_clip),
                optax.rmsprop(cfg.lr, decay=0.99, eps=0.1))
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        self.opt_state = self.optimizer.init(self.params)
        gamma = cfg.gamma

        def forward(p, batch):
            """-> (target_logp [T,B], values [T,B], entropy, boot_values
            [B]); memory models replay each fragment from its stored
            state, feedforward models evaluate flat."""
            T, B = batch[SampleBatch.REWARDS].shape
            obs = batch[SampleBatch.OBS]
            actions = batch[SampleBatch.ACTIONS]
            if recurrent:
                from ray_tpu.rl.recurrent import (memory_bootstrap_value,
                                                  memory_forward)
                boundary = (batch[SampleBatch.TERMINATEDS]
                            | batch[SampleBatch.TRUNCATEDS]
                            ).astype(jnp.float32)        # [T, B]
                resets = jnp.concatenate(
                    [jnp.zeros((1, B)), boundary[:-1]], axis=0)
                dist_in, values, final_state = memory_forward(
                    p, model_cfg, jnp.swapaxes(obs, 0, 1),
                    batch["state_in"][0],
                    jnp.swapaxes(resets, 0, 1))
                dist = _models.make_distribution(
                    p, jnp.swapaxes(dist_in, 0, 1), continuous)
                target_logp = dist.logp(actions)
                boot_values = memory_bootstrap_value(
                    p, model_cfg, batch["bootstrap_obs"][-1],
                    final_state * (1.0 - boundary[-1][:, None]))
                return (target_logp, jnp.swapaxes(values, 0, 1),
                        dist.entropy().mean(), boot_values)
            dist_in, values = _models.actor_critic_apply(
                p, obs.reshape((T * B,) + obs.shape[2:]))
            dist = _models.make_distribution(p, dist_in, continuous)
            flat_actions = actions.reshape((T * B,) + actions.shape[2:])
            return (dist.logp(flat_actions).reshape(T, B),
                    values.reshape(T, B), dist.entropy().mean(),
                    _models.actor_critic_apply(
                        p, batch["bootstrap_obs"][-1])[1])

        def update(params, opt_state, batch):
            # Columns arrive time-major [T, B, ...].
            def loss_fn(p):
                (target_logp, values, entropy,
                 boot_values) = forward(p, batch)
                # Truncation cuts the recursion too: the next in-fragment
                # row belongs to the auto-reset episode, so bootstrapping
                # across it would blend unrelated returns. Treating
                # truncation as terminal trades that leak for a small
                # no-bootstrap bias at time limits.
                boundary = (batch[SampleBatch.TERMINATEDS]
                            | batch[SampleBatch.TRUNCATEDS])
                discounts = gamma * (1.0 - boundary.astype(jnp.float32))
                vs, pg_adv = vtrace(
                    batch[SampleBatch.ACTION_LOGP], target_logp,
                    batch[SampleBatch.REWARDS], values,
                    jax.lax.stop_gradient(boot_values), discounts,
                    cfg.vtrace_clip_rho_threshold,
                    cfg.vtrace_clip_c_threshold)
                if clip_param is not None:
                    # APPO: PPO's clipped surrogate with the importance
                    # ratio against the BEHAVIOR policy, advantages from
                    # V-trace (off-policy corrected)
                    ratio = jnp.exp(target_logp
                                    - batch[SampleBatch.ACTION_LOGP])
                    pg_loss = -jnp.mean(_models.clipped_surrogate(
                        ratio, pg_adv, clip_param))
                else:
                    pg_loss = -jnp.mean(target_logp * pg_adv)
                vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
                total = (pg_loss + cfg.vf_loss_coeff * vf_loss
                         - cfg.entropy_coeff * entropy)
                return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                               "entropy": entropy}

            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def train(self, batch_tm: Dict[str, np.ndarray]) -> Dict[str, float]:
        arrays = {k: jnp.asarray(v) for k, v in batch_tm.items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, arrays)
        return {k: float(v) for k, v in aux.items()}

    def state(self):
        return jax.device_get((self.params, self.opt_state))

    def set_state(self, state):
        p, o = state
        self.params = jax.tree_util.tree_map(jnp.asarray, p)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, o)


class Impala(Algorithm):
    _config_cls = ImpalaConfig

    @classmethod
    def get_default_config(cls) -> ImpalaConfig:
        return ImpalaConfig(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _make_learner(self) -> ImpalaLearner:
        cfg = self.algo_config
        lw = self.workers.local_worker
        self._in_flight: Dict[Any, Any] = {}
        self._broadcast_countdown = 0
        return ImpalaLearner(lw.get_weights(), cfg, lw.policy.continuous,
                             clip_param=cfg.clip_param)

    def _to_time_major(self, batch: SampleBatch) -> Dict[str, np.ndarray]:
        T = self.algo_config.rollout_fragment_length
        n = (len(batch) // T) * T
        out = {}
        keys = [SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS,
                SampleBatch.ACTION_LOGP, "bootstrap_obs"]
        if "state_in" in batch:
            keys.append("state_in")  # memory models: fragment-start state
        for k in keys:
            v = batch[k][:n]
            out[k] = np.swapaxes(
                v.reshape((n // T, T) + v.shape[1:]), 0, 1)
        return out

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        if not self.workers.remote_workers:
            # Degenerate sync mode with only the local worker.
            batch = self.workers.local_worker.sample()
            batches = [batch]
        else:
            # Keep every worker saturated with in-flight sample requests.
            for w in self.workers.remote_workers:
                pending = sum(1 for ref, src in self._in_flight.items()
                              if src is w)
                for _ in range(
                        cfg.max_sample_requests_in_flight_per_worker
                        - pending):
                    self._in_flight[w.sample.remote()] = w
            ready, _ = ray_tpu.wait(
                list(self._in_flight), num_returns=1, timeout=30.0)
            from ray_tpu.exceptions import ActorDiedError
            batches = []
            stale_workers = set()
            for r in ready:
                w = self._in_flight.pop(r)
                try:
                    batches.append(ray_tpu.get(r))
                    stale_workers.add(w)
                except ActorDiedError:
                    fresh = self.workers.recreate_failed_worker(w)
                    # Drop the dead worker's other in-flight refs.
                    for ref, src in list(self._in_flight.items()):
                        if src is w:
                            self._in_flight.pop(ref)
                    stale_workers.add(fresh)
            # Async weight push: only refresh the workers just harvested
            # (reference broadcast_interval semantics).
            self._broadcast_countdown -= 1
            if self._broadcast_countdown <= 0:
                weights_ref = ray_tpu.put(
                    jax.device_get(self.learner.params))
                for w in stale_workers:
                    w.set_weights.remote(weights_ref)
                self._broadcast_countdown = cfg.broadcast_interval
        total = 0
        per_batch: List[Dict[str, float]] = []
        for batch in batches:
            tm = self._to_time_major(batch)
            per_batch.append(self.learner.train(tm))
            total += len(batch)
        self._timesteps_total += total
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner.params))
        if per_batch:
            metrics = {k: float(np.mean([m[k] for m in per_batch]))
                       for k in per_batch[0]}
        metrics["timesteps_this_iter"] = total
        return metrics

    def _learner_state(self):
        return {"learner": self.learner.state()}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])


class APPOConfig(ImpalaConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param = 0.3
        self.lr = 3e-3          # rmsprop, small async batches
        self.entropy_coeff = 0.005


class APPO(Impala):
    """Asynchronous PPO (``rllib/algorithms/appo``): IMPALA's
    architecture — asynchronous rollout workers, V-trace off-policy
    correction — with PPO's clipped surrogate as the policy objective.
    Pure configuration of the IMPALA learner (the clipped term is a
    branch inside the same compiled update)."""

    _config_cls = APPOConfig

    @classmethod
    def get_default_config(cls) -> APPOConfig:
        return APPOConfig(cls)
