"""Advantage Actor-Critic (synchronous A2C).

Parity with ``rllib/algorithms/a2c``: synchronous on-policy rollouts,
one vanilla policy-gradient pass per batch with a value-function
baseline and entropy bonus.

Implementation: the PPO learner evaluated at its fixed point. With ONE
sgd pass over freshly collected data, ``logp == logp_old`` so the
importance ratio is 1 everywhere; the gradient of ``ratio * adv`` then
equals ``grad logp * adv`` — the exact vanilla-PG estimator — and an
unbounded clip range plus ``kl_coeff=0`` removes the trust-region
machinery. A2C is therefore a CONFIG of the compiled PPO program, not a
second learner to maintain (same single-XLA-program schedule,
``ppo.py``).
"""

from __future__ import annotations

from ray_tpu.rl.ppo import PPO, PPOConfig


class A2CConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lr = 1e-3
        # ONE full-batch step per (small, frequent) batch — A2C's
        # classic shape. With minibatches, passes after the first would
        # run off-policy with no clip (unbounded ratio): the vanilla-PG
        # equivalence only holds at batch granularity.
        self.train_batch_size = 200
        self.sgd_minibatch_size = 200
        self.num_sgd_iter = 1       # single pass => exact vanilla PG
        self.clip_param = 1e9       # ratio is 1 on the first pass anyway
        self.kl_coeff = 0.0
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.rollout_fragment_length = 25


class A2C(PPO):
    _config_cls = A2CConfig

    @classmethod
    def get_default_config(cls) -> A2CConfig:
        return A2CConfig(cls)
