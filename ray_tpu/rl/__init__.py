"""ray_tpu.rl — reinforcement learning on TPU.

The TPU-native redesign of the reference's RLlib (``rllib/``, SURVEY §2.6):
``Algorithm`` is a Tune ``Trainable`` whose ``training_step`` composes
rollout collection from CPU env actors with a JAX learner compiled over a
device mesh. Where RLlib splits batches across GPU "towers" with loader
threads (``rllib/execution/multi_gpu_learner_thread.py``), here the batch is
sharded over the mesh's data axis and XLA inserts the gradient ``psum`` —
the tower logic is a sharding annotation, not an engine.
"""

from ray_tpu.rl.a2c import A2C, A2CConfig
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.connectors import (ClipActions, ClipObs, Connector,
                                   ConnectorPipeline, FlattenObs,
                                   FrameStack, NormalizeObs, ScaleActions,
                                   build_connectors, register_connector)
from ray_tpu.rl.ddpg import DDPG, DDPGConfig
from ray_tpu.rl.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.env import (CartPoleEnv, EnvSpec, MemoryCueEnv, PendulumEnv,
                            VectorEnv, make_env, register_env)
from ray_tpu.rl.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rl.external_env import ExternalEnv, ExternalEnvSampler
from ray_tpu.rl.qmix import QMIX, QMIXConfig
from ray_tpu.rl.recurrent import RecurrentPolicy
from ray_tpu.rl.impala import (APPO, APPOConfig, Impala,
                               ImpalaConfig)
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.ppo import PPO, PPOConfig
from ray_tpu.rl.multi_agent import (CoordinationGameEnv, MultiAgentBatch,
                                    MultiAgentEnv, MultiAgentPPO,
                                    MultiAgentPPOConfig,
                                    MultiAgentRolloutWorker,
                                    RockPaperScissorsEnv,
                                    TwoStepCooperativeGameEnv,
                                    register_multi_agent_env)
from ray_tpu.rl.offline import (BC, BCConfig, CQL, CQLConfig, MARWIL,
                                MARWILConfig, collect_dataset,
                                read_dataset, write_dataset)
from ray_tpu.rl.sac import SAC, SACConfig
from ray_tpu.rl.td3 import TD3, TD3Config
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer, ReplayBuffer)
from ray_tpu.rl.rollout_worker import (RolloutWorker, WorkerSet,
                                       synchronous_parallel_sample)
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples

__all__ = [
    "Algorithm", "AlgorithmConfig", "Policy", "SampleBatch", "concat_samples",
    "RolloutWorker", "WorkerSet", "synchronous_parallel_sample",
    "ReplayBuffer", "PrioritizedReplayBuffer",
    "PPO", "PPOConfig", "A2C", "A2CConfig", "DQN", "DQNConfig",
    "Impala", "ImpalaConfig", "APPO", "APPOConfig",
    "SAC", "SACConfig", "TD3", "TD3Config", "DDPG", "DDPGConfig",
    "DDPPO", "DDPPOConfig", "ES", "ESConfig", "ARS", "ARSConfig",
    "QMIX", "QMIXConfig", "RecurrentPolicy",
    "ExternalEnv", "ExternalEnvSampler",
    "Connector", "ConnectorPipeline", "build_connectors",
    "register_connector", "FlattenObs", "ClipObs", "NormalizeObs",
    "FrameStack", "ClipActions", "ScaleActions",
    "BC", "BCConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
    "collect_dataset", "read_dataset", "write_dataset",
    "MultiAgentEnv", "MultiAgentBatch", "MultiAgentRolloutWorker",
    "MultiAgentPPO", "MultiAgentPPOConfig", "CoordinationGameEnv",
    "RockPaperScissorsEnv", "TwoStepCooperativeGameEnv",
    "register_multi_agent_env",
    "CartPoleEnv", "MemoryCueEnv", "PendulumEnv", "VectorEnv", "EnvSpec",
    "make_env", "register_env",
]
