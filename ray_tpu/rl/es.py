"""Evolution Strategies (OpenAI-ES) and Augmented Random Search.

Parity with ``rllib/algorithms/es`` (Salimans et al. 2017) and
``rllib/algorithms/ars`` (Mania et al. 2018): derivative-free policy
search by antithetic parameter perturbations —

- ES: rank-shaped fitness over ALL directions, gradient estimate
  ``lr/(n*std) * sum(shaped(r+) - shaped(r-)) * delta``.
- ARS (V2): observation normalization, TOP-k directions by
  ``max(r+, r-)``, update scaled by the std of the used returns.

Runtime shape: perturbation evaluations are full-episode rollouts and
embarrassingly parallel — each direction's (+/-) pair runs as a
``ray_tpu`` remote task when ``num_rollout_workers > 0`` (the
reference's ES worker actors), or inline for ``0``. The policy is a
deterministic MLP over flattened parameters (``ravel_pytree``); the
perturbation/update math is plain numpy — there is no gradient tape
anywhere, which is the point of the algorithm family.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, Discrete, make_env


def _mlp_shapes(obs_dim: int, hidden: Tuple[int, ...], out_dim: int):
    dims = (obs_dim,) + tuple(hidden) + (out_dim,)
    return [(dims[i], dims[i + 1]) for i in range(len(dims) - 1)]


def _param_size(shapes) -> int:
    return sum(i * o + o for i, o in shapes)


def _policy_act(theta: np.ndarray, shapes, obs: np.ndarray,
                discrete: bool, lo, hi) -> np.ndarray:
    """Deterministic MLP forward from the flat parameter vector."""
    x = obs
    off = 0
    for n, (i, o) in enumerate(shapes):
        w = theta[off:off + i * o].reshape(i, o)
        off += i * o
        b = theta[off:off + o]
        off += o
        x = x @ w + b
        if n < len(shapes) - 1:
            x = np.tanh(x)
    if discrete:
        return int(np.argmax(x))
    return np.clip(np.tanh(x) * (hi - lo) / 2 + (hi + lo) / 2, lo, hi)


def _rollout(env_name, env_config, theta, shapes, discrete, lo, hi,
             max_steps: int, obs_stats: Optional[tuple], seed: int):
    """One full episode; returns (return, steps, obs_sum, obs_sq, n)."""
    env = make_env(env_name, dict(env_config or {}, seed=seed))
    obs = np.asarray(env.reset(seed=seed), np.float64)
    mean, std = (obs_stats if obs_stats is not None
                 else (np.zeros_like(obs), np.ones_like(obs)))
    total = 0.0
    o_sum = np.zeros_like(obs)
    o_sq = np.zeros_like(obs)
    steps = 0
    for _ in range(max_steps):
        o_sum += obs
        o_sq += obs * obs
        norm = (obs - mean) / std
        a = _policy_act(theta, shapes, norm, discrete, lo, hi)
        obs, rew, terminated, truncated, _ = env.step(a)
        obs = np.asarray(obs, np.float64)
        total += float(rew)
        steps += 1
        if terminated or truncated:
            break
    return total, steps, o_sum, o_sq, steps


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.num_perturbations = 16    # antithetic pairs per iteration
        self.noise_std = 0.1
        self.step_size = 0.05          # the "lr" of the ES update
        self.episode_horizon = 1000
        self.top_frac = 1.0            # ARS sets < 1
        self.observation_filter = False  # ARS sets True (V2)
        self.model = {"fcnet_hiddens": (32,)}
        self.num_rollout_workers = 0


class ES(Algorithm):
    """OpenAI-ES (``rllib/algorithms/es/es.py:1`` role)."""

    _config_cls = ESConfig

    @classmethod
    def get_default_config(cls) -> ESConfig:
        return ESConfig(cls)

    # ES has no gradient learner and no sampling worker set: setup builds
    # the flat parameter vector + env probe instead.
    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if cfg.env is None:
            raise ValueError("AlgorithmConfig.environment(env=...) not set")
        probe = make_env(cfg.env, dict(cfg.env_config or {}))
        space = probe.spec.action_space
        self._discrete = isinstance(space, Discrete)
        if self._discrete:
            out_dim = space.n
            self._lo = self._hi = None
        elif isinstance(space, Box):
            out_dim = int(np.prod(space.shape))
            self._lo = np.asarray(space.low, np.float64).reshape(-1)
            self._hi = np.asarray(space.high, np.float64).reshape(-1)
        else:
            raise ValueError(f"unsupported action space {space}")
        obs_dim = int(np.prod(probe.spec.observation_space.shape))
        self._shapes = _mlp_shapes(
            obs_dim, tuple(cfg.model.get("fcnet_hiddens", (32,))), out_dim)
        self._rng = np.random.default_rng(cfg.seed or 0)
        self.theta = (self._rng.standard_normal(_param_size(self._shapes))
                      * 0.1)
        # running observation stats (ARS V2 normalization)
        self._obs_n = 1e-4
        self._obs_sum = np.zeros(obs_dim)
        self._obs_sq = np.ones(obs_dim) * 1e-4
        self._iter = 0
        self._remote_rollout = None
        if cfg.num_rollout_workers > 0:
            import ray_tpu
            self._remote_rollout = ray_tpu.remote(
                num_cpus=cfg.num_cpus_per_worker)(_rollout)

    def _obs_stats(self):
        if not self.algo_config.observation_filter:
            return None
        mean = self._obs_sum / self._obs_n
        var = np.maximum(self._obs_sq / self._obs_n - mean ** 2, 1e-8)
        return mean, np.sqrt(var)

    def _evaluate(self, thetas: List[np.ndarray]) -> List[tuple]:
        """Episode returns for each candidate, remote when configured."""
        cfg = self.algo_config
        stats = self._obs_stats()
        seed = (cfg.seed or 0) * 100_003 + self._iter
        args = [(cfg.env, cfg.env_config, th, self._shapes, self._discrete,
                 self._lo, self._hi, cfg.episode_horizon, stats, seed + i)
                for i, th in enumerate(thetas)]
        if self._remote_rollout is not None:
            import ray_tpu
            return ray_tpu.get(
                [self._remote_rollout.remote(*a) for a in args],
                timeout=600)
        return [_rollout(*a) for a in args]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        n = cfg.num_perturbations
        self._iter += 1
        deltas = self._rng.standard_normal((n, self.theta.size))
        cands = [self.theta + cfg.noise_std * d for d in deltas]
        cands += [self.theta - cfg.noise_std * d for d in deltas]
        results = self._evaluate(cands)
        r_pos = np.array([r[0] for r in results[:n]])
        r_neg = np.array([r[0] for r in results[n:]])
        steps = int(sum(r[1] for r in results))
        for _, _, o_sum, o_sq, cnt in results:
            self._obs_n += cnt
            self._obs_sum += o_sum
            self._obs_sq += o_sq
        self.theta = self._update(deltas, r_pos, r_neg)
        self._timesteps_total += steps
        # evaluation episode with the CURRENT (unperturbed) params
        ev = _rollout(cfg.env, cfg.env_config, self.theta, self._shapes,
                      self._discrete, self._lo, self._hi,
                      cfg.episode_horizon, self._obs_stats(),
                      seed=self._iter)
        self._episode_history.append(
            {"episode_reward": ev[0], "episode_len": ev[1]})
        return {"timesteps_this_iter": steps,
                "perturbation_reward_mean":
                    float(np.mean(np.concatenate([r_pos, r_neg])))}

    def _update(self, deltas, r_pos, r_neg) -> np.ndarray:
        """OpenAI-ES: centered-rank shaping over all 2n returns."""
        cfg = self.algo_config
        all_r = np.concatenate([r_pos, r_neg])
        ranks = np.empty(all_r.size)
        ranks[np.argsort(all_r)] = np.arange(all_r.size)
        shaped = ranks / (all_r.size - 1) - 0.5
        sp, sn = shaped[:len(r_pos)], shaped[len(r_pos):]
        grad = ((sp - sn)[:, None] * deltas).sum(0) / (
            len(r_pos) * cfg.noise_std)
        return self.theta + cfg.step_size * grad

    # ES reports its own episodes; no worker set exists.
    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        self._episode_history = self._episode_history[-100:]
        rewards = [e["episode_reward"] for e in self._episode_history]
        lengths = [e["episode_len"] for e in self._episode_history]
        result["episode_reward_mean"] = float(np.mean(rewards))
        result["episode_reward_max"] = float(np.max(rewards))
        result["episode_len_mean"] = float(np.mean(lengths))
        result["episodes_this_iter"] = 1
        result["timesteps_total"] = self._timesteps_total
        result["sample_throughput"] = (
            result.get("timesteps_this_iter", 0)
            / max(1e-9, time.time() - t0))
        return result

    def get_weights(self):
        return {"theta": np.array(self.theta)}

    def set_weights(self, weights):
        self.theta = np.array(weights["theta"])

    def _learner_state(self):
        return {"obs_n": self._obs_n, "obs_sum": self._obs_sum,
                "obs_sq": self._obs_sq, "iter": self._iter}

    def _set_learner_state(self, state):
        if state:
            self._obs_n = state["obs_n"]
            self._obs_sum = state["obs_sum"]
            self._obs_sq = state["obs_sq"]
            self._iter = state["iter"]

    def cleanup(self):
        pass


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.top_frac = 0.5
        self.observation_filter = True  # ARS V2
        self.noise_std = 0.03
        self.step_size = 0.02
        self.model = {"fcnet_hiddens": ()}  # linear policies (the paper)


class ARS(ES):
    """Augmented Random Search (``rllib/algorithms/ars/ars.py:1`` role)."""

    _config_cls = ARSConfig

    @classmethod
    def get_default_config(cls) -> ARSConfig:
        return ARSConfig(cls)

    def _update(self, deltas, r_pos, r_neg) -> np.ndarray:
        cfg = self.algo_config
        k = max(1, int(round(cfg.top_frac * len(r_pos))))
        order = np.argsort(np.maximum(r_pos, r_neg))[::-1][:k]
        used = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = used.std() or 1.0
        grad = ((r_pos[order] - r_neg[order])[:, None]
                * deltas[order]).sum(0) / (k * sigma_r)
        return self.theta + cfg.step_size * grad
