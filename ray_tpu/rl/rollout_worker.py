"""Rollout collection: env-stepping workers and the set that manages them.

Parity with ``rllib/evaluation/rollout_worker.py`` (``RolloutWorker.sample``),
``worker_set.py`` (``WorkerSet``, ``sync_weights``) and
``rllib/execution/rollout_ops.py:36`` (``synchronous_parallel_sample``).
Workers are CPU actors stepping numpy envs; the policy network runs in the
worker's JAX-CPU context. The learner never sees an env.
"""

from __future__ import annotations
import logging

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import VectorEnv, make_env
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.postprocessing import compute_gae
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples

logger = logging.getLogger("ray_tpu")


class RolloutWorker:
    """Steps a VectorEnv with the current policy, emitting SampleBatches.

    Plain class — usable inline (local worker) or as a ``ray_tpu`` actor
    (remote workers), same as the reference's dual-use RolloutWorker.
    """

    def __init__(self, env_name_or_maker, env_config: Optional[dict] = None,
                 num_envs: int = 1, rollout_fragment_length: int = 200,
                 policy_config: Optional[dict] = None, seed: int = 0,
                 worker_index: int = 0,
                 policy_cls: Callable[..., Policy] = Policy,
                 gamma: float = 0.99, lambda_: float = 0.95,
                 compute_advantages: bool = True):
        base_seed = seed + worker_index * 10007
        from ray_tpu.rl.external_env import ExternalEnv, ExternalEnvSampler
        probe = make_env(env_name_or_maker, dict(env_config or {}))
        if isinstance(probe, ExternalEnv):
            # Application-driven env: sampling SERVICES its queue instead
            # of stepping it (reference external_env.py integration).
            from ray_tpu.rl.connectors import ConnectorPipeline
            self.obs_connectors = ConnectorPipeline([])
            self.action_connectors = ConnectorPipeline([])
            self.policy = policy_cls(probe.spec, policy_config,
                                     seed=base_seed)
            self._external = ExternalEnvSampler(
                probe, self.policy, fragment_length=rollout_fragment_length,
                gamma=gamma, lambda_=lambda_,
                compute_advantages=compute_advantages)
            self.vector_env = None
            self.fragment_length = rollout_fragment_length
            self.gamma, self.lambda_ = gamma, lambda_
            self.compute_advantages = compute_advantages
            self.worker_index = worker_index
            self._spec = probe.spec
            return
        self._external = None
        self.vector_env = VectorEnv(
            lambda c: make_env(env_name_or_maker, c), num_envs,
            env_config, seed=base_seed)
        self.fragment_length = rollout_fragment_length
        self.gamma = gamma
        self.lambda_ = lambda_
        self.compute_advantages = compute_advantages
        self.worker_index = worker_index
        # Connector pipelines (rllib/connectors role): obs transforms on
        # the way in, action transforms on the way out, built per worker
        # from (name, kwargs) specs in the model config.
        from ray_tpu.rl.connectors import ConnectorPipeline, build_connectors
        cfg = dict(policy_config or {})
        self.obs_connectors = ConnectorPipeline(
            build_connectors(cfg.get("obs_connectors")))
        self.action_connectors = ConnectorPipeline(
            build_connectors(cfg.get("action_connectors")))
        self.action_connectors.bind_space(self.vector_env.spec.action_space)
        self._obs = self._transform_obs(
            self.vector_env.reset(seed=base_seed))
        # Connectors may reshape observations (frame stacking): the
        # policy must be built against the TRANSFORMED shape.
        spec = self.vector_env.spec
        obs_shape = np.asarray(self._obs).shape[1:]
        if tuple(obs_shape) != tuple(spec.observation_space.shape):
            from dataclasses import replace as _dc_replace
            from ray_tpu.rl.env import Box as _Box
            spec = _dc_replace(spec, observation_space=_Box(
                -np.inf, np.inf, tuple(obs_shape)))
        self.policy = policy_cls(spec, policy_config, seed=base_seed)
        self._eps_ids = np.arange(num_envs, dtype=np.int64)
        self._next_eps_id = num_envs
        self._eps_return = np.zeros(num_envs, np.float64)
        self._eps_len = np.zeros(num_envs, np.int64)
        self._completed: List[dict] = []

    def _transform_obs(self, obs):
        if not self.obs_connectors.connectors:
            return obs
        return self.obs_connectors(obs)

    def _peek_obs(self, obs):
        """Transform WITHOUT advancing connector state (bootstrap-value
        observations are side looks, not steps)."""
        if not self.obs_connectors.connectors:
            return obs
        return self.obs_connectors.peek(obs)

    def sample(self) -> SampleBatch:
        """Collect ``fragment_length`` steps per sub-env (column-major)."""
        if self._external is not None:
            return self._external.sample()
        n_envs = self.vector_env.num_envs
        T = self.fragment_length
        cols: Dict[str, list] = {k: [] for k in (
            SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS,
            SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS,
            SampleBatch.EPS_ID, "bootstrap_values")}
        # Stateful (recurrent/attention) policies: snapshot the per-env
        # recurrent state at fragment START — the learner replays each
        # fragment from it (rllib's state_in_0 / seq_lens contract).
        get_state = getattr(self.policy, "get_recurrent_state", None)
        state0 = get_state(n_envs) if get_state is not None else None
        for _ in range(T):
            actions, logp, values = self.policy.compute_actions(self._obs)
            env_actions = (self.action_connectors(actions)
                           if self.action_connectors.connectors
                           else actions)
            obs2, rews, terms, truncs, infos = self.vector_env.step(
                env_actions)
            boots = np.zeros(n_envs, np.float32)
            trunc_idx = [i for i in range(n_envs)
                         if truncs[i] and not terms[i]]
            if trunc_idx:
                term_obs = np.stack(
                    [infos[i]["terminal_obs"] for i in trunc_idx])
                term_obs = self._peek_obs(term_obs)
                if state0 is not None:
                    # stateful policy: value for a SUBSET of envs needs
                    # the matching state rows
                    vals = self.policy.value(term_obs,
                                             env_indices=trunc_idx)
                else:
                    vals = self.policy.value(term_obs)
                for j, i in enumerate(trunc_idx):
                    boots[i] = vals[j]
            cols[SampleBatch.OBS].append(self._obs)
            cols[SampleBatch.ACTIONS].append(actions)
            cols[SampleBatch.REWARDS].append(rews)
            cols[SampleBatch.TERMINATEDS].append(terms)
            cols[SampleBatch.TRUNCATEDS].append(truncs)
            cols[SampleBatch.ACTION_LOGP].append(logp)
            cols[SampleBatch.VF_PREDS].append(values)
            cols[SampleBatch.EPS_ID].append(self._eps_ids.copy())
            cols["bootstrap_values"].append(boots)
            self._eps_return += rews
            self._eps_len += 1
            done_idx = []
            for i in range(n_envs):
                if terms[i] or truncs[i]:
                    done_idx.append(i)
                    self._completed.append({
                        "episode_reward": float(self._eps_return[i]),
                        "episode_len": int(self._eps_len[i])})
                    self._eps_return[i] = 0.0
                    self._eps_len[i] = 0
                    self._eps_ids[i] = self._next_eps_id
                    self._next_eps_id += 1
            if done_idx:
                # recurrent policies must not carry memory across the
                # episode boundary (the sub-env auto-reset)
                reset_hook = getattr(self.policy, "on_episode_end", None)
                if reset_hook is not None:
                    reset_hook(done_idx)
                self.obs_connectors.on_episode_end(done_idx)
            self._obs = self._transform_obs(obs2)

        # Per-env fragments so GAE recursion never crosses env boundaries.
        stacked = {k: np.stack(v) for k, v in cols.items()}  # [T, n_envs,...]
        # Bootstrap obs for the step after the fragment end: the live obs,
        # or the pre-reset terminal obs if the final step truncated.
        boot_obs = np.asarray(self._obs).copy()
        for i in range(n_envs):
            if truncs[i] and not terms[i] and "terminal_obs" in infos[i]:
                # self._obs is already connector-transformed; a raw
                # terminal obs must go through the same (peeked) pipe
                boot_obs[i] = self._peek_obs(
                    np.asarray(infos[i]["terminal_obs"])[None])[0]
        last_values = self.policy.value(boot_obs)
        frags = []
        for i in range(n_envs):
            frag = SampleBatch({k: v[:, i] for k, v in stacked.items()})
            if state0 is not None:
                # broadcast per step so concat/shuffle stays rectangular;
                # the learner reads row 0 of each T-block
                frag["state_in"] = np.repeat(
                    np.asarray(state0[i])[None], T, 0)
            if self.compute_advantages:
                compute_gae(frag, float(last_values[i]),
                            self.gamma, self.lambda_)
            else:
                # Off-policy learners (V-trace) re-evaluate values with the
                # learner's own network; ship the bootstrap obs per step
                # (broadcast per fragment) so no worker-side values leak in.
                frag["bootstrap_obs"] = np.repeat(boot_obs[i][None], T, 0)
            frags.append(frag)
        return concat_samples(frags)

    def pop_metrics(self) -> List[dict]:
        if self._external is not None:
            return self._external.pop_metrics()
        out, self._completed = self._completed, []
        return out

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def get_connector_state(self):
        return self.obs_connectors.state()

    def set_connector_state(self, state) -> None:
        self.obs_connectors.set_state(state)

    def get_spec(self):
        if self._external is not None:
            return self._spec
        return self.vector_env.spec

    def apply(self, fn: Callable[["RolloutWorker"], Any]) -> Any:
        return fn(self)

    def stop(self) -> None:
        pass


class WorkerSet:
    """A local worker + N remote worker actors (``worker_set.py``).

    Dead remote workers are transparently recreated and re-synced on the
    next operation that touches them (the reference's
    ``recreate_failed_workers``, ``worker_set.py``)."""

    def __init__(self, num_workers: int, worker_kwargs: Dict[str, Any],
                 num_cpus_per_worker: float = 1.0):
        import ray_tpu
        self.local_worker = RolloutWorker(worker_index=0, **worker_kwargs)
        self._worker_kwargs = dict(worker_kwargs)
        self._num_cpus_per_worker = num_cpus_per_worker
        self._remote_cls = ray_tpu.remote(RolloutWorker)
        self.remote_workers = [self._spawn(i + 1)
                               for i in range(num_workers)]

    def _spawn(self, worker_index: int):
        return self._remote_cls.options(
            num_cpus=self._num_cpus_per_worker).remote(
                worker_index=worker_index, **self._worker_kwargs)

    def recreate_failed_worker(self, worker) -> Any:
        """Replace a dead worker handle with a fresh actor carrying the
        local worker's current weights."""
        import ray_tpu
        i = self.remote_workers.index(worker)
        fresh = self._spawn(i + 1)
        fresh.set_weights.remote(self.local_worker.get_weights())
        self.remote_workers[i] = fresh
        return fresh

    def sync_weights(self) -> None:
        """Broadcast local weights to remotes (``ppo.py:427-430``)."""
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError
        if not self.remote_workers:
            return
        weights_ref = ray_tpu.put(self.local_worker.get_weights())
        for w, ref in [(w, w.set_weights.remote(weights_ref))
                       for w in list(self.remote_workers)]:
            try:
                ray_tpu.get(ref)
            except ActorDiedError:
                self.recreate_failed_worker(w)
        # Connector statistics flow the OTHER way: the SAMPLING workers
        # own the running obs stats (they see the data); the local worker
        # adopts a sampler's stats so evaluation/learner-side transforms
        # match. Pushing local->remote would wipe the learned stats with
        # the local worker's empty ones every iteration.
        try:
            state = ray_tpu.get(
                self.remote_workers[0].get_connector_state.remote())
            if state and any(s is not None for s in state):
                self.local_worker.set_connector_state(state)
        except (ActorDiedError, IndexError):
            pass

    def foreach_worker(self, fn: Callable[[RolloutWorker], Any]) -> List[Any]:
        import ray_tpu
        results = [fn(self.local_worker)]
        if self.remote_workers:
            results += ray_tpu.get(
                [w.apply.remote(fn) for w in self.remote_workers])
        return results

    def collect_metrics(self) -> List[dict]:
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError
        episodes = self.local_worker.pop_metrics()
        for w, ref in [(w, w.pop_metrics.remote())
                       for w in list(self.remote_workers)]:
            try:
                episodes.extend(ray_tpu.get(ref))
            except ActorDiedError:
                self.recreate_failed_worker(w)
        return episodes

    def stop(self) -> None:
        import ray_tpu
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception as e:
                logger.debug("worker kill failed: %s", e)
        self.remote_workers = []


def synchronous_parallel_sample(workers: WorkerSet,
                                max_env_steps: Optional[int] = None
                                ) -> SampleBatch:
    """Round-robin sample() across all workers until the step budget is met
    (reference: ``rollout_ops.py:36``)."""
    import ray_tpu
    from ray_tpu.exceptions import ActorDiedError
    batches: List[SampleBatch] = []
    total = 0
    while True:
        round_batches = []
        if workers.remote_workers:
            refs = [(w, w.sample.remote()) for w in workers.remote_workers]
            for w, ref in refs:
                try:
                    round_batches.append(ray_tpu.get(ref))
                except ActorDiedError:
                    workers.recreate_failed_worker(w)
        else:
            round_batches = [workers.local_worker.sample()]
        for b in round_batches:
            batches.append(b)
            total += len(b)
        if max_env_steps is None or total >= max_env_steps:
            break
    return concat_samples(batches)
