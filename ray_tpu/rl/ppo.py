"""Proximal Policy Optimization.

Parity with ``rllib/algorithms/ppo/ppo.py`` (training_step :400-470:
synchronous sampling -> advantage standardization -> minibatch SGD ->
weight sync -> adaptive KL update) and ``ppo_torch_policy.py`` (clipped
surrogate + clipped value loss + entropy bonus + KL penalty).

TPU-first learner: where the reference splits the batch across GPU towers
with loader threads (``multi_gpu_train_one_step``, ``train_ops.py:98``),
here the entire ``num_sgd_iter`` x minibatch schedule — permutations
included — is ONE compiled XLA program (``lax.scan`` over epochs and
minibatches), entered with a single host->device transfer of the sample
batch. On a mesh, the batch dim is sharded over the ``data`` axis and XLA
inserts the gradient psum over ICI.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.postprocessing import standardize
from ray_tpu.rl.rollout_worker import synchronous_parallel_sample
from ray_tpu.rl.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 30
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 1.0
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.lambda_ = 0.95
        self.grad_clip = 0.5


class PPOLearner:
    """Compiled PPO update. Holds (params, opt_state) on device."""

    def __init__(self, init_params, cfg: PPOConfig, continuous: bool,
                 mesh=None):
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        self.opt_state = self.optimizer.init(self.params)
        self.rng = jax.random.key(cfg.seed + 7919)
        self._continuous = continuous
        self._train = self._build_train_fn()

    def _build_train_fn(self):
        cfg = self.cfg
        continuous = self._continuous
        optimizer = self.optimizer
        mb = cfg.sgd_minibatch_size

        def loss_fn(params, kl_coeff, batch):
            dist_in, values = _models.actor_critic_apply(
                params, batch[SampleBatch.OBS])
            dist = _models.make_distribution(params, dist_in, continuous)
            return _models.ppo_surrogate_loss(dist, values, batch, cfg,
                                              kl_coeff)

        def train_fn(params, opt_state, rng, kl_coeff, batch):
            n = batch[SampleBatch.OBS].shape[0]
            num_mb = max(1, n // mb)

            def epoch(carry, _):
                params, opt_state, rng = carry
                rng, key = jax.random.split(rng)
                perm = jax.random.permutation(key, n)
                shuffled = jax.tree_util.tree_map(
                    lambda x: x[perm][:num_mb * mb].reshape(
                        (num_mb, mb) + x.shape[1:]), batch)

                def mb_step(c, minibatch):
                    p, o = c
                    (_, aux), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(p, kl_coeff, minibatch)
                    updates, o = optimizer.update(grads, o, p)
                    p = optax.apply_updates(p, updates)
                    return (p, o), aux

                (params, opt_state), auxs = jax.lax.scan(
                    mb_step, (params, opt_state), shuffled)
                return (params, opt_state, rng), auxs

            (params, opt_state, rng), auxs = jax.lax.scan(
                epoch, (params, opt_state, rng), None,
                length=cfg.num_sgd_iter)
            metrics = jax.tree_util.tree_map(lambda x: jnp.mean(x), auxs)
            last_kl = jnp.mean(auxs["kl"][-1])
            metrics["kl"] = last_kl
            return params, opt_state, rng, metrics

        return jax.jit(train_fn, donate_argnums=(0, 1))

    def train(self, batch: SampleBatch, kl_coeff: float) -> Dict[str, float]:
        from ray_tpu.rl.sample_batch import batch_to_device
        used = SampleBatch({k: v for k, v in batch.items()
                            if k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                                     SampleBatch.ACTION_LOGP,
                                     SampleBatch.ADVANTAGES,
                                     SampleBatch.VALUE_TARGETS)})
        sharding = None
        if self.mesh is not None:
            # Batch spec comes from the rules table (("data", "fsdp")),
            # not a bare P("data"): on an fsdp-bearing mesh the jitted
            # train_fn would otherwise reshard every minibatch.
            from ray_tpu.parallel.sharding import batch_sharding
            sharding = batch_sharding(self.mesh, ndim=1)
        arrays = batch_to_device(used, sharding)
        self.params, self.opt_state, self.rng, metrics = self._train(
            self.params, self.opt_state, self.rng,
            jnp.asarray(kl_coeff, jnp.float32), arrays)
        return {k: float(v) for k, v in metrics.items()}

    def state(self):
        return jax.device_get((self.params, self.opt_state))

    def set_state(self, state):
        params, opt_state = state
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)


class PPO(Algorithm):
    _config_cls = PPOConfig

    @classmethod
    def get_default_config(cls) -> PPOConfig:
        return PPOConfig(cls)

    def _make_learner(self) -> PPOLearner:
        cfg = self.algo_config
        lw = self.workers.local_worker
        self.kl_coeff = cfg.kl_coeff
        from ray_tpu.rl.recurrent import (RecurrentPPOLearner,
                                          uses_memory_model)
        if uses_memory_model(cfg.model):
            return RecurrentPPOLearner(lw.get_weights(), cfg,
                                       lw.policy.continuous,
                                       cfg.rollout_fragment_length)
        return PPOLearner(lw.get_weights(), cfg, lw.policy.continuous,
                          mesh=cfg.mesh)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=cfg.train_batch_size)
        self._timesteps_total += len(batch)
        # Batch-level advantage standardization (ppo.py:415).
        batch[SampleBatch.ADVANTAGES] = standardize(
            batch[SampleBatch.ADVANTAGES])
        # Pad to the static train_batch_size so XLA compiles once. The
        # sequence learner shapes its own batches (slicing here could
        # cut a fragment mid-sequence).
        if not getattr(self.learner, "handles_batch_shaping", False):
            n = (len(batch) // cfg.sgd_minibatch_size
                 ) * cfg.sgd_minibatch_size
            if n == 0:
                batch = batch.pad_to(cfg.sgd_minibatch_size)
            else:
                batch = batch.slice(0, n)
        metrics = self.learner.train(batch, self.kl_coeff)
        # Adaptive KL coefficient (ppo.py:433-437).
        kl = metrics["kl"]
        if kl > 2.0 * cfg.kl_target:
            self.kl_coeff *= 1.5
        elif kl < 0.5 * cfg.kl_target:
            self.kl_coeff *= 0.5
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner.params))
        metrics.update(timesteps_this_iter=len(batch),
                       kl_coeff=self.kl_coeff,
                       learner_params=_models.num_params(self.learner.params))
        return metrics

    def _learner_state(self):
        return {"learner": self.learner.state(), "kl_coeff": self.kl_coeff}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])
            self.kl_coeff = state["kl_coeff"]
