"""Algorithm base: config builder + Trainable integration.

Parity with ``rllib/algorithms/algorithm.py`` (Algorithm is a Tune
``Trainable`` whose ``step`` drives ``training_step``) and
``algorithm_config.py`` (the fluent ``AlgorithmConfig`` builder:
``.environment().rollouts().training().resources()``).
"""

from __future__ import annotations

import pickle
import os
import time
from typing import Any, Dict, List, Optional, Type

import numpy as np

from ray_tpu.rl.rollout_worker import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent builder; ``.build()`` instantiates the algorithm."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        # environment()
        self.env = None
        self.env_config: Dict[str, Any] = {}
        # rollouts()
        self.num_rollout_workers = 0
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.num_cpus_per_worker = 1.0
        # training()
        self.gamma = 0.99
        self.lr = 5e-4
        self.train_batch_size = 4000
        self.model: Dict[str, Any] = {}
        self.seed = 0
        # framework/resources()
        self.mesh = None  # optional jax Mesh for the learner
        self.extra: Dict[str, Any] = {}

    def environment(self, env=None, env_config: Optional[dict] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def rollouts(self, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_cpus_per_worker: Optional[float] = None
                 ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_cpus_per_worker is not None:
            self.num_cpus_per_worker = num_cpus_per_worker
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def resources(self, mesh=None) -> "AlgorithmConfig":
        if mesh is not None:
            self.mesh = mesh
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class", "extra")}
        d.update(self.extra)
        return d

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(config=self)


class Algorithm(Trainable):
    """Base RL algorithm. Subclasses override ``get_default_config`` and
    ``training_step`` (reference: ``algorithm.py`` ``training_step``)."""

    _config_cls = AlgorithmConfig

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._config_cls(cls)

    def __init__(self, config=None, env=None, logdir: Optional[str] = None):
        if isinstance(config, AlgorithmConfig):
            self.algo_config = config
        else:
            self.algo_config = self.get_default_config()
            for k, v in (config or {}).items():
                if hasattr(self.algo_config, k):
                    setattr(self.algo_config, k, v)
                else:
                    self.algo_config.extra[k] = v
        if env is not None:
            self.algo_config.env = env
        self._episode_history: List[dict] = []
        self._timesteps_total = 0
        super().__init__(config=self.algo_config.to_dict(), logdir=logdir)

    # -- Trainable plumbing ----------------------------------------------

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if cfg.env is None:
            raise ValueError("AlgorithmConfig.environment(env=...) not set")
        self.workers = self._make_worker_set()
        self.learner = self._make_learner()

    def _worker_kwargs(self) -> Dict[str, Any]:
        cfg = self.algo_config
        kw = dict(
            env_name_or_maker=cfg.env,
            env_config=cfg.env_config,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_config=dict(cfg.model),
            seed=cfg.seed,
            gamma=cfg.gamma,
            lambda_=getattr(cfg, "lambda_", 0.95),
            compute_advantages=self._needs_advantages(),
        )
        # Model catalog seam (rllib/models/catalog.py use_lstm /
        # use_attention): memory models swap in the stateful policy.
        from ray_tpu.rl.recurrent import RecurrentPolicy, uses_memory_model
        if uses_memory_model(cfg.model):
            kw["policy_cls"] = RecurrentPolicy
        return kw

    def _needs_advantages(self) -> bool:
        return True

    def _make_worker_set(self) -> WorkerSet:
        cfg = self.algo_config
        return WorkerSet(cfg.num_rollout_workers, self._worker_kwargs(),
                         num_cpus_per_worker=cfg.num_cpus_per_worker)

    def _make_learner(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step() or {}
        episodes = self.workers.collect_metrics()
        self._episode_history.extend(episodes)
        self._episode_history = self._episode_history[-100:]
        if self._episode_history:
            rewards = [e["episode_reward"] for e in self._episode_history]
            lengths = [e["episode_len"] for e in self._episode_history]
            result["episode_reward_mean"] = float(np.mean(rewards))
            result["episode_reward_min"] = float(np.min(rewards))
            result["episode_reward_max"] = float(np.max(rewards))
            result["episode_len_mean"] = float(np.mean(lengths))
        result["episodes_this_iter"] = len(episodes)
        result["timesteps_total"] = self._timesteps_total
        result["sample_throughput"] = (
            result.get("timesteps_this_iter", 0) / max(1e-9, time.time() - t0))
        return result

    # -- checkpointing ----------------------------------------------------

    def get_weights(self):
        return self.workers.local_worker.get_weights()

    def set_weights(self, weights):
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights()

    def save_checkpoint(self, checkpoint_dir: str) -> Any:
        state = self.__getstate__()
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)
        return state

    def load_checkpoint(self, checkpoint: Any):
        if checkpoint is None:
            return
        if isinstance(checkpoint, str):
            with open(os.path.join(checkpoint, "algorithm_state.pkl"),
                      "rb") as f:
                checkpoint = pickle.load(f)
        self.__setstate__(checkpoint)

    def __getstate__(self) -> Dict[str, Any]:
        return {
            "weights": self.get_weights(),
            "learner_state": self._learner_state(),
            "timesteps_total": self._timesteps_total,
        }

    def __setstate__(self, state: Dict[str, Any]):
        self.set_weights(state["weights"])
        self._set_learner_state(state.get("learner_state"))
        self._timesteps_total = state.get("timesteps_total", 0)

    def _learner_state(self) -> Any:
        return None

    def _set_learner_state(self, state: Any) -> None:
        pass

    def cleanup(self):
        self.workers.stop()

    def stop(self):
        self.cleanup()
