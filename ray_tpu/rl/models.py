"""Policy/value networks and action distributions in pure JAX.

Parity with ``rllib/models/`` (``catalog.py`` fcnet defaults,
``torch/torch_action_dist.py`` Categorical/DiagGaussian). Networks are
(init, apply) pairs over pytrees so they compose with pjit sharding the
same way the model layer in ``ray_tpu.models`` does.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(key: jax.Array, in_dim: int, hidden: Sequence[int],
             out_dim: int, out_scale: float = 0.01) -> Dict[str, Any]:
    """Orthogonal-init MLP; small final layer like RLlib's fcnet."""
    sizes = [in_dim, *hidden, out_dim]
    layers = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        w = jax.nn.initializers.orthogonal(
            jnp.sqrt(2.0) if i < len(sizes) - 2 else out_scale)(
                k, (a, b), jnp.float32)
        layers.append({"w": w, "b": jnp.zeros((b,), jnp.float32)})
    return {"layers": layers}


def mlp_apply(params: Dict[str, Any], x: jax.Array,
              activation: str = "tanh") -> jax.Array:
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    *hidden_layers, last = params["layers"]
    for lyr in hidden_layers:
        x = act(x @ lyr["w"] + lyr["b"])
    return x @ last["w"] + last["b"]


def actor_critic_init(key: jax.Array, obs_dim: int, action_dim: int,
                      hidden: Sequence[int] = (64, 64),
                      continuous: bool = False) -> Dict[str, Any]:
    kp, kv = jax.random.split(key)
    params = {
        "pi": mlp_init(kp, obs_dim, hidden, action_dim),
        "vf": mlp_init(kv, obs_dim, hidden, 1, out_scale=1.0),
    }
    if continuous:
        params["log_std"] = jnp.zeros((action_dim,), jnp.float32)
    return params


def actor_critic_apply(params, obs) -> Tuple[jax.Array, jax.Array]:
    """-> (distribution inputs [B, A], value estimates [B])."""
    logits = mlp_apply(params["pi"], obs)
    values = mlp_apply(params["vf"], obs)[..., 0]
    return logits, values


def ppo_surrogate_loss(dist, values, batch, cfg, kl_coeff):
    """The PPO loss body shared by PPOLearner, RecurrentPPOLearner and
    the DD-PPO workers: clipped surrogate + clipped vf error + entropy
    bonus + logp-ratio KL penalty (one copy of the math; the callers
    differ only in how (dist, values) were produced).

    ``batch`` needs OBS-aligned ACTIONS / ACTION_LOGP / ADVANTAGES /
    VALUE_TARGETS. Returns (total_loss, aux dict).
    """
    from ray_tpu.rl.sample_batch import SampleBatch
    logp = dist.logp(batch[SampleBatch.ACTIONS])
    ratio = jnp.exp(logp - batch[SampleBatch.ACTION_LOGP])
    surrogate = clipped_surrogate(ratio, batch[SampleBatch.ADVANTAGES],
                                  cfg.clip_param)
    vf_err = jnp.minimum(
        (values - batch[SampleBatch.VALUE_TARGETS]) ** 2,
        cfg.vf_clip_param ** 2)
    entropy = dist.entropy()
    # Adaptive-KL penalty vs the behavior logp (rllib uses dist KL
    # against the old dist; the logp-ratio estimator
    # E[logp_old - logp] has the same fixed point and needs no old
    # dist params on device).
    kl = jnp.maximum(batch[SampleBatch.ACTION_LOGP] - logp, -10.0)
    total = (-jnp.mean(surrogate)
             + cfg.vf_loss_coeff * 0.5 * jnp.mean(vf_err)
             - cfg.entropy_coeff * jnp.mean(entropy)
             + kl_coeff * jnp.mean(kl))
    aux = {"policy_loss": -jnp.mean(surrogate),
           "vf_loss": 0.5 * jnp.mean(vf_err),
           "entropy": jnp.mean(entropy),
           "kl": jnp.mean(kl)}
    return total, aux


class Categorical:
    """Categorical over logits (rllib TorchCategorical equivalent)."""

    def __init__(self, logits: jax.Array):
        self.logits = logits - jax.scipy.special.logsumexp(
            logits, axis=-1, keepdims=True)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1)

    def logp(self, actions: jax.Array) -> jax.Array:
        return jnp.take_along_axis(
            self.logits, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        p = jnp.exp(self.logits)
        return -jnp.sum(p * self.logits, axis=-1)

    def kl(self, other: "Categorical") -> jax.Array:
        p = jnp.exp(self.logits)
        return jnp.sum(p * (self.logits - other.logits), axis=-1)

    def deterministic(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)


class DiagGaussian:
    """Diagonal gaussian with state-independent log_std."""

    def __init__(self, mean: jax.Array, log_std: jax.Array):
        self.mean = mean
        self.log_std = jnp.broadcast_to(log_std, mean.shape)

    def sample(self, key: jax.Array) -> jax.Array:
        return self.mean + jnp.exp(self.log_std) * jax.random.normal(
            key, self.mean.shape)

    def logp(self, actions: jax.Array) -> jax.Array:
        var = jnp.exp(2 * self.log_std)
        ll = (-0.5 * ((actions - self.mean) ** 2 / var)
              - self.log_std - 0.5 * jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    def entropy(self) -> jax.Array:
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e),
                       axis=-1)

    def kl(self, other: "DiagGaussian") -> jax.Array:
        v0, v1 = jnp.exp(2 * self.log_std), jnp.exp(2 * other.log_std)
        return jnp.sum(other.log_std - self.log_std
                       + (v0 + (self.mean - other.mean) ** 2) / (2 * v1)
                       - 0.5, axis=-1)

    def deterministic(self) -> jax.Array:
        return self.mean


def make_distribution(params: Dict[str, Any], dist_inputs: jax.Array,
                      continuous: bool):
    if continuous:
        return DiagGaussian(dist_inputs, params["log_std"])
    return Categorical(dist_inputs)


def clipped_surrogate(ratio: jax.Array, advantages: jax.Array,
                      clip_param: float) -> jax.Array:
    """PPO's pessimistic clipped objective, elementwise (shared by the
    PPO and APPO learners so the two cannot drift)."""
    return jnp.minimum(
        ratio * advantages,
        jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * advantages)


def num_params(params: Any) -> int:
    return int(sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params)))
