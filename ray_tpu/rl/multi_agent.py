"""Multi-agent environments and the independent-learner trainer.

Parity with ``rllib/env/multi_agent_env.py`` (dict-keyed obs/action/reward
protocol with the ``__all__`` done flag) and the independent-policies
multi-agent path of ``rllib/algorithms/algorithm.py`` (``policies`` +
``policy_mapping_fn`` config, per-policy sample batches, one learner per
policy — RLlib's default when parameter sharing is off).

The trainer composes the existing single-agent machinery: each policy_id
gets its own ``PPOLearner`` (``ppo.py``) and the multi-agent rollout
worker demultiplexes the env's dict streams into per-policy
``SampleBatch`` fragments with per-agent GAE.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box, Discrete, EnvSpec
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.postprocessing import compute_gae, standardize
from ray_tpu.rl.ppo import PPOConfig, PPOLearner
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples


class MultiAgentEnv:
    """Dict-keyed multi-agent protocol (``multi_agent_env.py:MultiAgentEnv``).

    ``reset`` returns ``{agent_id: obs}``; ``step(action_dict)`` returns
    ``(obs, rewards, terminateds, truncateds, infos)`` dicts. The
    terminateds/truncateds dicts carry the special ``"__all__"`` key that
    ends the episode for every agent.
    """

    agent_ids: Tuple[str, ...] = ()
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError


class CoordinationGameEnv(MultiAgentEnv):
    """Repeated 2-player coordination game (independent-learner gate env).

    Both agents pick an action in {0, 1} each step; payoff 1.0 to both if
    both pick 0, 0.3 if both pick 1, 0 on mismatch — a unique
    payoff-dominant equilibrium that independent learners must find
    without communication. Observation is the one-hot of the previous
    joint action (4-dim), zeros on reset.
    """

    agent_ids = ("agent_0", "agent_1")

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        obs_space = Box(0.0, 1.0, (4,))
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        self.action_spaces = {a: Discrete(2) for a in self.agent_ids}
        self.episode_len = int(config.get("episode_len", 25))
        self._t = 0
        self._last = np.zeros(4, np.float32)

    def reset(self, seed: Optional[int] = None):
        self._t = 0
        self._last = np.zeros(4, np.float32)
        return {a: self._last.copy() for a in self.agent_ids}

    def step(self, actions: Dict[str, Any]):
        a0 = int(actions["agent_0"])
        a1 = int(actions["agent_1"])
        if a0 == 0 and a1 == 0:
            r = 1.0
        elif a0 == 1 and a1 == 1:
            r = 0.3
        else:
            r = 0.0
        self._last = np.zeros(4, np.float32)
        self._last[a0 * 2 + a1] = 1.0
        self._t += 1
        done = self._t >= self.episode_len
        obs = {a: self._last.copy() for a in self.agent_ids}
        rews = {a: r for a in self.agent_ids}
        terms = {a: False for a in self.agent_ids}
        truncs = {a: done for a in self.agent_ids}
        terms["__all__"] = False
        truncs["__all__"] = done
        return obs, rews, terms, truncs, {a: {} for a in self.agent_ids}


class TwoStepCooperativeGameEnv(MultiAgentEnv):
    """The QMIX paper's two-step cooperative matrix game (Rashid et al.
    2018, §6.1): agent_0's first action picks payoff matrix A or B; in
    the second step both agents act and the TEAM receives the matrix
    payoff. Matrix A pays 7 everywhere; matrix B pays [[0,1],[1,8]].
    The optimal joint policy (pick B, then both play 1) earns 8 — but a
    purely additive value factorization (VDN) converges to the safe 7,
    which is exactly the representational gap QMIX's monotonic mixing
    closes. Observation: one-hot of the phase (start/A/B) per agent;
    ``get_state()`` exposes the same as the mixer's global state."""

    agent_ids = ("agent_0", "agent_1")

    def __init__(self, config: Optional[dict] = None):
        obs_space = Box(0.0, 1.0, (3,))
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        self.action_spaces = {a: Discrete(2) for a in self.agent_ids}
        self._phase = 0  # 0 = start, 1 = matrix A, 2 = matrix B

    def _obs(self):
        o = np.zeros(3, np.float32)
        o[self._phase] = 1.0
        return {a: o.copy() for a in self.agent_ids}

    def get_state(self) -> np.ndarray:
        s = np.zeros(3, np.float32)
        s[self._phase] = 1.0
        return s

    def reset(self, seed: Optional[int] = None):
        self._phase = 0
        return self._obs()

    def step(self, actions: Dict[str, Any]):
        if self._phase == 0:
            self._phase = 1 if int(actions["agent_0"]) == 0 else 2
            r, done = 0.0, False
        else:
            a0, a1 = int(actions["agent_0"]), int(actions["agent_1"])
            if self._phase == 1:
                r = 7.0
            else:
                r = [[0.0, 1.0], [1.0, 8.0]][a0][a1]
            done = True
        obs = self._obs()
        rews = {a: r for a in self.agent_ids}
        terms = {a: done for a in self.agent_ids}
        truncs = {a: False for a in self.agent_ids}
        terms["__all__"] = done
        truncs["__all__"] = False
        return obs, rews, terms, truncs, {a: {} for a in self.agent_ids}


class RockPaperScissorsEnv(MultiAgentEnv):
    """Zero-sum repeated RPS (``rllib/examples/env/rock_paper_scissors``).

    API-coverage env: competitive rewards, per-agent observation of the
    opponent's last move.
    """

    agent_ids = ("player_0", "player_1")
    _BEATS = {0: 2, 1: 0, 2: 1}  # rock beats scissors, ...

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        obs_space = Box(0.0, 1.0, (3,))
        self.observation_spaces = {a: obs_space for a in self.agent_ids}
        self.action_spaces = {a: Discrete(3) for a in self.agent_ids}
        self.episode_len = int(config.get("episode_len", 10))
        self._t = 0
        self._last = {a: np.zeros(3, np.float32) for a in self.agent_ids}

    def reset(self, seed: Optional[int] = None):
        self._t = 0
        self._last = {a: np.zeros(3, np.float32) for a in self.agent_ids}
        return {a: v.copy() for a, v in self._last.items()}

    def step(self, actions):
        m0, m1 = int(actions["player_0"]), int(actions["player_1"])
        if m0 == m1:
            r0 = r1 = 0.0
        elif self._BEATS[m0] == m1:
            r0, r1 = 1.0, -1.0
        else:
            r0, r1 = -1.0, 1.0
        self._last["player_0"] = np.eye(3, dtype=np.float32)[m1]
        self._last["player_1"] = np.eye(3, dtype=np.float32)[m0]
        self._t += 1
        done = self._t >= self.episode_len
        obs = {a: v.copy() for a, v in self._last.items()}
        return (obs, {"player_0": r0, "player_1": r1},
                {"player_0": False, "player_1": False, "__all__": False},
                {"player_0": done, "player_1": done, "__all__": done},
                {a: {} for a in self.agent_ids})


class MultiAgentBatch(dict):
    """policy_id -> SampleBatch (reference ``sample_batch.MultiAgentBatch``)."""

    def __init__(self, *args, env_step_count: Optional[int] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._env_step_count = env_step_count

    @property
    def env_steps(self) -> int:
        """True environment steps — NOT agent rows (with shared policies a
        policy batch holds one row per agent per env step)."""
        if self._env_step_count is not None:
            return self._env_step_count
        return max((len(b) for b in self.values()), default=0)

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.values())


class MultiAgentRolloutWorker:
    """Steps a MultiAgentEnv, demultiplexing per-policy SampleBatches.

    Plain class like ``RolloutWorker`` — works inline or as a ray_tpu
    actor. One Policy instance per policy_id; ``policy_mapping_fn``
    routes agent_ids to policies.
    """

    def __init__(self, env_maker: Callable[[dict], MultiAgentEnv],
                 env_config: Optional[dict] = None,
                 policy_mapping_fn: Optional[Callable[[str], str]] = None,
                 policies: Optional[Dict[str, dict]] = None,
                 rollout_fragment_length: int = 200,
                 policy_config: Optional[dict] = None, seed: int = 0,
                 worker_index: int = 0,
                 policy_cls: Callable[..., Policy] = Policy,
                 gamma: float = 0.99, lambda_: float = 0.95):
        self.env = env_maker(dict(env_config or {}))
        self.mapping = policy_mapping_fn or (lambda aid: aid)
        self.fragment_length = rollout_fragment_length
        self.gamma, self.lambda_ = gamma, lambda_
        self.worker_index = worker_index
        policy_ids = sorted({self.mapping(a) for a in self.env.agent_ids})
        self.policies: Dict[str, Policy] = {}
        for k, pid in enumerate(policy_ids):
            # spec from any agent mapped to this policy
            aid = next(a for a in self.env.agent_ids
                       if self.mapping(a) == pid)
            spec = EnvSpec(self.env.observation_spaces[aid],
                           self.env.action_spaces[aid],
                           max_episode_steps=10_000)
            cfg = dict(policy_config or {})
            if policies and pid in policies:
                cfg.update(policies[pid] or {})
            self.policies[pid] = policy_cls(
                spec, cfg, seed=seed + worker_index * 10007 + k)
        self._obs = self.env.reset(seed=seed + worker_index * 10007)
        self._eps_id = 0
        self._eps_return = 0.0
        self._eps_len = 0
        self._completed: List[dict] = []

    def sample(self) -> MultiAgentBatch:
        # Collect per AGENT (not per policy): with shared policies, rows
        # from different agents must not interleave before GAE — the
        # values[t+1] recursion would pair one agent's step with the
        # other's. Group into policy batches only after advantages exist.
        keys = (SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS,
                SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS,
                SampleBatch.EPS_ID, "bootstrap_values")
        cols: Dict[str, Dict[str, list]] = {
            aid: {k: [] for k in keys} for aid in self.env.agent_ids}
        for _ in range(self.fragment_length):
            actions, logps, vfs = {}, {}, {}
            for aid, ob in self._obs.items():
                pid = self.mapping(aid)
                a, lp, vf = self.policies[pid].compute_actions(ob[None])
                actions[aid] = a[0]
                logps[aid], vfs[aid] = lp[0], vf[0]
            obs2, rews, terms, truncs, _ = self.env.step(actions)
            for aid in self._obs:
                c = cols[aid]
                term = terms.get(aid, False) or terms.get("__all__", False)
                trunc = truncs.get(aid, False) or truncs.get(
                    "__all__", False)
                # time-limit truncation bootstraps from V(terminal obs),
                # matching the single-agent path (rollout_worker.py)
                boot = 0.0
                if trunc and not term and aid in obs2:
                    boot = float(self.policies[self.mapping(aid)].value(
                        obs2[aid][None])[0])
                c[SampleBatch.OBS].append(self._obs[aid])
                c[SampleBatch.ACTIONS].append(actions[aid])
                c[SampleBatch.REWARDS].append(rews.get(aid, 0.0))
                c[SampleBatch.TERMINATEDS].append(term)
                c[SampleBatch.TRUNCATEDS].append(trunc)
                c[SampleBatch.ACTION_LOGP].append(logps[aid])
                c[SampleBatch.VF_PREDS].append(vfs[aid])
                c[SampleBatch.EPS_ID].append(self._eps_id)
                c["bootstrap_values"].append(boot)
            self._eps_return += float(np.mean(
                [rews.get(a, 0.0) for a in self._obs]))
            self._eps_len += 1
            done = terms.get("__all__", False) or truncs.get("__all__", False)
            if done:
                self._completed.append({
                    "episode_reward": self._eps_return,
                    "episode_len": self._eps_len})
                self._eps_return, self._eps_len = 0.0, 0
                self._eps_id += 1
                self._obs = self.env.reset()
            else:
                self._obs = obs2

        per_policy: Dict[str, List[SampleBatch]] = {
            pid: [] for pid in self.policies}
        for aid, c in cols.items():
            pid = self.mapping(aid)
            batch = SampleBatch({k: np.asarray(v) for k, v in c.items()})
            # GAE per episode segment; bootstrap the live tail with the
            # policy's value of this agent's current obs
            for frag in batch.split_by_episode():
                last_trunc = bool(frag[SampleBatch.TRUNCATEDS][-1])
                last_term = bool(frag[SampleBatch.TERMINATEDS][-1])
                if last_term or last_trunc:
                    last_v = 0.0  # compute_gae reads bootstrap_values
                else:
                    last_v = float(self.policies[pid].value(
                        self._obs[aid][None])[0])
                compute_gae(frag, last_v, self.gamma, self.lambda_)
                per_policy[pid].append(frag)
        return MultiAgentBatch(
            {pid: concat_samples(frags)
             for pid, frags in per_policy.items() if frags},
            env_step_count=self.fragment_length)

    def pop_metrics(self) -> List[dict]:
        out, self._completed = self._completed, []
        return out

    def get_weights(self) -> Dict[str, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            if pid in self.policies:
                self.policies[pid].set_weights(w)

    def apply(self, fn):
        return fn(self)

    def stop(self) -> None:
        pass


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MultiAgentPPO)
        self.policies: Dict[str, dict] = {}
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        self.train_batch_size = 400
        self.sgd_minibatch_size = 64
        self.num_sgd_iter = 10

    def multi_agent(self, policies: Optional[Dict[str, dict]] = None,
                    policy_mapping_fn: Optional[Callable[[str], str]] = None
                    ) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    """Independent PPO learners, one per policy_id (the reference's
    default multi-agent mode: no parameter sharing, per-policy updates)."""

    _config_cls = MultiAgentPPOConfig

    @classmethod
    def get_default_config(cls) -> MultiAgentPPOConfig:
        return MultiAgentPPOConfig(cls)

    def _make_worker_set(self):
        cfg = self.algo_config
        env = cfg.env
        maker = env if callable(env) else _ma_registry_maker(env)
        worker = MultiAgentRolloutWorker(
            maker, env_config=cfg.env_config,
            policy_mapping_fn=cfg.policy_mapping_fn,
            policies=cfg.policies,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_config=dict(cfg.model), seed=cfg.seed,
            gamma=cfg.gamma, lambda_=getattr(cfg, "lambda_", 0.95))
        return _LocalOnlyWorkerSet(worker)

    def _make_learner(self) -> Dict[str, PPOLearner]:
        cfg = self.algo_config
        lw = self.workers.local_worker
        self.kl_coeff = {pid: cfg.kl_coeff for pid in lw.policies}
        return {pid: PPOLearner(pol.get_weights(), cfg, pol.continuous,
                                mesh=cfg.mesh)
                for pid, pol in lw.policies.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        lw = self.workers.local_worker
        collected: Dict[str, List[SampleBatch]] = {
            pid: [] for pid in lw.policies}
        steps = 0
        while steps < cfg.train_batch_size:
            ma = lw.sample()
            steps += ma.env_steps
            for pid, b in ma.items():
                collected[pid].append(b)
        metrics: Dict[str, Any] = {"timesteps_this_iter": steps}
        self._timesteps_total += steps
        for pid, batches in collected.items():
            batch = concat_samples(batches)
            batch[SampleBatch.ADVANTAGES] = standardize(
                batch[SampleBatch.ADVANTAGES])
            n = (len(batch) // cfg.sgd_minibatch_size
                 ) * cfg.sgd_minibatch_size
            batch = (batch.slice(0, n) if n
                     else batch.pad_to(cfg.sgd_minibatch_size))
            m = self.learner[pid].train(batch, self.kl_coeff[pid])
            kl = m["kl"]
            if kl > 2.0 * cfg.kl_target:
                self.kl_coeff[pid] *= 1.5
            elif kl < 0.5 * cfg.kl_target:
                self.kl_coeff[pid] *= 0.5
            lw.policies[pid].set_weights(
                jax.device_get(self.learner[pid].params))
            metrics[pid] = m
        return metrics

    def _learner_state(self):
        return {"learners": {pid: ln.state()
                             for pid, ln in self.learner.items()},
                "kl_coeff": dict(self.kl_coeff)}

    def _set_learner_state(self, state):
        if state:
            for pid, s in state["learners"].items():
                self.learner[pid].set_state(s)
            self.kl_coeff = dict(state["kl_coeff"])

    def get_weights(self):
        return self.workers.local_worker.get_weights()

    def set_weights(self, weights):
        self.workers.local_worker.set_weights(weights)


class _LocalOnlyWorkerSet:
    """WorkerSet shim for the (local-only) multi-agent worker."""

    def __init__(self, worker: MultiAgentRolloutWorker):
        self.local_worker = worker
        self.remote_workers: list = []

    def sync_weights(self) -> None:
        pass

    def collect_metrics(self) -> List[dict]:
        return self.local_worker.pop_metrics()

    def stop(self) -> None:
        self.local_worker.stop()


_MA_REGISTRY: Dict[str, Callable[[dict], MultiAgentEnv]] = {
    "CoordinationGame": lambda c: CoordinationGameEnv(c),
    "RockPaperScissors": lambda c: RockPaperScissorsEnv(c),
}


def _ma_registry_maker(name: str) -> Callable[[dict], MultiAgentEnv]:
    if name not in _MA_REGISTRY:
        raise KeyError(f"Unknown multi-agent env {name!r}; registered: "
                       f"{sorted(_MA_REGISTRY)}")
    return _MA_REGISTRY[name]


def register_multi_agent_env(name: str,
                             maker: Callable[[dict], MultiAgentEnv]) -> None:
    _MA_REGISTRY[name] = maker
