"""DD-PPO: decentralized distributed PPO.

Parity with ``rllib/algorithms/ddppo/ddppo.py:91,131-152`` (Wijmans et
al. 2020): there is NO central learner — each rollout worker trains on
its OWN locally-collected batch and synchronizes by ALLREDUCING
GRADIENTS with its peers, so sample collection and SGD both scale with
the worker count and no batch or weight tensors ever flow through the
driver. All workers start from identical parameters and apply identical
averaged updates, so their policies stay bit-identical without any
weight broadcast.

The gradient exchange rides this package's collective library
(``ray_tpu.util.collective``): each DD-PPO worker joins one collective
group and allreduces its flattened gradient pytree every SGD iteration
— on TPU pods the same program shape rides ICI via the xla backend.
"""

from __future__ import annotations
import logging

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.env import Box, make_env
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.ppo import PPOConfig
from ray_tpu.rl.rollout_worker import RolloutWorker
from ray_tpu.rl.sample_batch import SampleBatch

logger = logging.getLogger("ray_tpu")


class DDPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPPO)
        self.num_rollout_workers = 2   # the gradient-allreduce world
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 0    # 0 = whole local batch per step
        self.train_batch_size = 512    # PER WORKER (DD-PPO semantics)
        self.collective_backend = "cpu"


class _DDPPOWorker:
    """One decentralized worker: rollout sampling + local SGD with
    per-iteration gradient allreduce. Runs as a ``ray_tpu`` actor."""

    def __init__(self, worker_kwargs: Dict[str, Any], cfg_dict: Dict,
                 init_weights: Dict, rank: int, world_size: int,
                 group_name: str):
        cfg = DDPPOConfig()
        for k, v in cfg_dict.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        self.cfg = cfg
        self.rank = rank
        self.world = world_size
        self.group = group_name
        self.worker = RolloutWorker(worker_index=rank, **worker_kwargs)
        # identical start everywhere: decentralized sync only works if
        # every peer applies identical updates to identical params
        self.worker.set_weights(init_weights)
        self.params = jax.tree_util.tree_map(
            jnp.asarray, self.worker.get_weights())
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        self._continuous = isinstance(
            self.worker.vector_env.spec.action_space, Box)
        self._grad_fn = self._build_grad_fn()
        from ray_tpu import collective as col
        col.init_collective_group(world_size, rank,
                                  backend=cfg.collective_backend,
                                  group_name=group_name)

    def _build_grad_fn(self):
        cfg = self.cfg
        continuous = self._continuous

        def loss_fn(params, kl_coeff, batch):
            dist_in, values = _models.actor_critic_apply(
                params, batch[SampleBatch.OBS])
            dist = _models.make_distribution(params, dist_in, continuous)
            return _models.ppo_surrogate_loss(dist, values, batch, cfg,
                                              kl_coeff)

        return jax.jit(jax.grad(loss_fn, has_aux=True))

    def run_iteration(self, kl_coeff: float) -> Dict[str, Any]:
        """One DD-PPO iteration: sample locally, then num_sgd_iter rounds
        of (local grad -> allreduce-mean -> identical apply)."""
        from jax.flatten_util import ravel_pytree
        from ray_tpu import collective as col
        from ray_tpu.rl.sample_batch import concat_samples
        cfg = self.cfg
        batch = concat_samples(
            [self.worker.sample() for _ in range(
                max(1, cfg.train_batch_size
                    // max(1, cfg.rollout_fragment_length
                           * self.worker.vector_env.num_envs)))])
        arrays = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()
                  if k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                           SampleBatch.ACTION_LOGP, SampleBatch.ADVANTAGES,
                           SampleBatch.VALUE_TARGETS)}
        aux = {}
        for _ in range(cfg.num_sgd_iter):
            grads, aux = self._grad_fn(
                self.params, jnp.asarray(kl_coeff, jnp.float32), arrays)
            flat, unravel = ravel_pytree(grads)
            # THE DD-PPO step: gradients — not weights — cross workers
            summed = col.allreduce(np.asarray(flat),
                                   group_name=self.group)
            mean = jnp.asarray(summed) / self.world
            updates, self.opt_state = self.optimizer.update(
                unravel(mean), self.opt_state, self.params)
            self.params = optax.apply_updates(self.params, updates)
        self.worker.set_weights(jax.device_get(self.params))
        return {"steps": len(batch),
                "metrics": {k: float(v) for k, v in aux.items()},
                "episodes": self.worker.pop_metrics()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        """Checkpoint restore: replace params everywhere they live; the
        optimizer restarts fresh (documented restore semantics)."""
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
        self.opt_state = self.optimizer.init(self.params)
        self.worker.set_weights(jax.device_get(self.params))
        return True


class DDPPO(Algorithm):
    _config_cls = DDPPOConfig

    @classmethod
    def get_default_config(cls) -> DDPPOConfig:
        return DDPPOConfig(cls)

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if cfg.env is None:
            raise ValueError("AlgorithmConfig.environment(env=...) not set")
        if cfg.num_rollout_workers < 2:
            raise ValueError("DD-PPO is a decentralized strategy: "
                             "num_rollout_workers must be >= 2")
        wk = dict(
            env_name_or_maker=cfg.env, env_config=cfg.env_config,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_config=dict(cfg.model), seed=cfg.seed,
            gamma=cfg.gamma, lambda_=getattr(cfg, "lambda_", 0.95),
            compute_advantages=True)
        # identical init params, minted once
        probe = make_env(cfg.env, dict(cfg.env_config or {}))
        init = Policy(probe.spec, dict(cfg.model),
                      seed=cfg.seed).get_weights()
        n = cfg.num_rollout_workers
        group = f"ddppo-{id(self)}"
        cls = ray_tpu.remote(num_cpus=cfg.num_cpus_per_worker)(
            _DDPPOWorker)
        self._workers = [
            cls.remote(wk, cfg.to_dict(), init, rank, n, group)
            for rank in range(n)]
        self._kl_coeff = cfg.kl_coeff
        # wait for construction (collective join is rendezvous-blocking)
        ray_tpu.get([w.get_weights.remote() for w in self._workers],
                    timeout=120)

    def training_step(self) -> Dict[str, Any]:
        outs = ray_tpu.get(
            [w.run_iteration.remote(self._kl_coeff)
             for w in self._workers], timeout=600)
        steps = sum(o["steps"] for o in outs)
        self._timesteps_total += steps
        for o in outs:
            self._episode_history.extend(o["episodes"])
        kl = float(np.mean([o["metrics"].get("kl", 0.0) for o in outs]))
        cfg = self.algo_config
        if kl > 2.0 * cfg.kl_target:
            self._kl_coeff *= 1.5
        elif kl < 0.5 * cfg.kl_target:
            self._kl_coeff *= 0.5
        agg = {k: float(np.mean([o["metrics"][k] for o in outs]))
               for k in outs[0]["metrics"]}
        agg.update(timesteps_this_iter=steps, kl_coeff=self._kl_coeff)
        return agg

    # workers ARE the learners; episode metrics flow through training_step
    def step(self) -> Dict[str, Any]:
        import time as _time
        t0 = _time.time()
        result = self.training_step()
        self._episode_history = self._episode_history[-100:]
        if self._episode_history:
            rewards = [e["episode_reward"] for e in self._episode_history]
            result["episode_reward_mean"] = float(np.mean(rewards))
        result["timesteps_total"] = self._timesteps_total
        result["sample_throughput"] = (
            result.get("timesteps_this_iter", 0)
            / max(1e-9, _time.time() - t0))
        return result

    def get_weights(self):
        return ray_tpu.get(self._workers[0].get_weights.remote(),
                           timeout=60)

    def set_weights(self, weights):
        """Broadcast identical weights to EVERY worker — the only write
        that preserves the lockstep invariant (each worker also resets
        its optimizer state, so peers stay bit-identical)."""
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self._workers], timeout=120)

    def __getstate__(self):
        return {"weights": self.get_weights(),
                "timesteps_total": self._timesteps_total}

    def __setstate__(self, state):
        if state.get("weights") is not None:
            self.set_weights(state["weights"])
        self._timesteps_total = state.get("timesteps_total", 0)

    def cleanup(self):
        for w in getattr(self, "_workers", []):
            try:
                ray_tpu.kill(w)
            except Exception as e:
                logger.debug("worker kill failed: %s", e)
