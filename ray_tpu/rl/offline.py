"""Offline RL: dataset IO, Behavior Cloning, and Conservative Q-Learning.

Parity with the reference's offline stack
(``rllib/offline/json_reader.py``/``json_writer.py`` — SampleBatch
datasets on disk; ``rllib/algorithms/bc/bc.py`` — supervised policy
cloning; ``rllib/algorithms/cql/cql.py`` — SAC with the conservative
Q regularizer for learning from fixed datasets without online
exploration).

TPU-first: an offline "rollout" is just a minibatch slice of the
dataset, so training is pure supervised/TD compute — the whole epoch
runs as jitted steps with no env in the loop. Datasets are columnar
``.npz`` shards (numpy's native container), not JSON: loads are
zero-parse and feed device transfers directly.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples

# ---------------------------------------------------------------- dataset IO


def write_dataset(batch: SampleBatch, path: str) -> str:
    """Write one columnar shard (``json_writer.py`` role, npz format)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(path, **{k: np.asarray(v) for k, v in batch.items()})
    return path


def read_dataset(path_or_glob: str) -> SampleBatch:
    """Read shard(s) into one SampleBatch (``json_reader.py`` role)."""
    paths = sorted(_glob.glob(path_or_glob)) or [path_or_glob]
    parts = []
    for p in paths:
        with np.load(p) as z:
            parts.append(SampleBatch({k: z[k] for k in z.files}))
    return concat_samples(parts)


def collect_dataset(env_name_or_maker, policy=None, n_steps: int = 1000,
                    seed: int = 0, env_config: Optional[dict] = None
                    ) -> SampleBatch:
    """Roll a (possibly random) behavior policy to build a dataset."""
    env = make_env(env_name_or_maker, env_config)
    rng = np.random.default_rng(seed)
    obs = env.reset(seed=seed)
    cols: Dict[str, List[Any]] = {k: [] for k in (
        SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
        SampleBatch.NEXT_OBS, SampleBatch.TERMINATEDS,
        SampleBatch.TRUNCATEDS)}
    for _ in range(n_steps):
        if policy is None:
            action = env.spec.action_space.sample(rng)
        else:
            a, _, _ = policy.compute_actions(obs[None])
            action = a[0]
        obs2, rew, term, trunc, _ = env.step(action)
        cols[SampleBatch.OBS].append(obs)
        cols[SampleBatch.ACTIONS].append(action)
        cols[SampleBatch.REWARDS].append(rew)
        cols[SampleBatch.NEXT_OBS].append(obs2)
        cols[SampleBatch.TERMINATEDS].append(term)
        cols[SampleBatch.TRUNCATEDS].append(trunc)
        obs = env.reset() if (term or trunc) else obs2
    if cols[SampleBatch.TERMINATEDS]:
        # collection may stop mid-episode: mark the seam, or return
        # computations over CONCATENATED datasets would leak rewards
        # across shard boundaries
        if not (cols[SampleBatch.TERMINATEDS][-1]
                or cols[SampleBatch.TRUNCATEDS][-1]):
            cols[SampleBatch.TRUNCATEDS][-1] = True
    return SampleBatch({k: np.asarray(v) for k, v in cols.items()})


# ---------------------------------------------------------------- BC


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.n_updates_per_iter = 32
        self.input_ = None   # SampleBatch | path/glob (reference: config.offline_data)
        self.model = {"fcnet_hiddens": (64, 64)}


class BC(Algorithm):
    """Behavior Cloning (``rllib/algorithms/bc``): supervised max-logp of
    dataset actions. No env interaction; ``env`` is only used for spaces
    (and optional evaluation)."""

    _config_cls = BCConfig

    @classmethod
    def get_default_config(cls) -> BCConfig:
        return BCConfig(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _make_worker_set(self):
        # env workers exist only to expose spaces + run evaluation rollouts
        from ray_tpu.rl.rollout_worker import WorkerSet
        kw = self._worker_kwargs()
        kw["rollout_fragment_length"] = 200
        return WorkerSet(0, kw)

    def _load_dataset(self) -> SampleBatch:
        inp = getattr(self.algo_config, "input_", None)
        if inp is None:
            raise ValueError("BC/CQL require .training(input_=...) — a "
                             "SampleBatch or an npz path/glob")
        if isinstance(inp, str):
            return read_dataset(inp)
        return inp

    def _make_learner(self):
        cfg = self.algo_config
        self.dataset = self._load_dataset()
        lw = self.workers.local_worker
        pol = lw.policy
        self._continuous = pol.continuous
        self._rng = np.random.default_rng(cfg.seed)
        params = jax.tree_util.tree_map(jnp.asarray, pol.params)
        optimizer = optax.adam(cfg.lr)
        opt_state = optimizer.init(params)
        continuous = self._continuous

        def bc_step(params, opt_state, obs, actions):
            def loss_fn(p):
                dist_in, _ = _models.actor_critic_apply(p, obs)
                dist = _models.make_distribution(p, dist_in, continuous)
                return -jnp.mean(dist.logp(actions))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = optimizer.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        self._step = jax.jit(bc_step)
        return {"params": params, "opt_state": opt_state}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        n = len(self.dataset)
        losses = []
        for _ in range(cfg.n_updates_per_iter):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            obs = jnp.asarray(self.dataset[SampleBatch.OBS][idx],
                              jnp.float32)
            act = jnp.asarray(self.dataset[SampleBatch.ACTIONS][idx])
            (self.learner["params"], self.learner["opt_state"],
             loss) = self._step(self.learner["params"],
                                self.learner["opt_state"], obs, act)
            losses.append(float(loss))
        self._timesteps_total += cfg.n_updates_per_iter * cfg.train_batch_size
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner["params"]))
        return {"bc_loss": float(np.mean(losses)),
                "timesteps_this_iter": cfg.n_updates_per_iter
                * cfg.train_batch_size,
                "dataset_size": n}

    def evaluate(self, n_episodes: int = 5) -> float:
        """Greedy rollout return of the cloned policy."""
        lw = self.workers.local_worker
        total = []
        for ep in range(n_episodes):
            env = lw.vector_env.envs[0]
            obs = env.reset(seed=1000 + ep)
            ep_ret, done = 0.0, False
            while not done:
                a, _, _ = lw.policy.compute_actions(obs[None], explore=False)
                obs, r, term, trunc, _ = env.step(a[0])
                ep_ret += r
                done = term or trunc
            total.append(ep_ret)
        return float(np.mean(total))

    def _learner_state(self):
        return jax.device_get((self.learner["params"],
                               self.learner["opt_state"]))

    def _set_learner_state(self, state):
        if state:
            p, o = state
            self.learner["params"] = jax.tree_util.tree_map(jnp.asarray, p)
            self.learner["opt_state"] = jax.tree_util.tree_map(
                jnp.asarray, o)


# -------------------------------------------------------------- MARWIL


class MARWILConfig(BCConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta = 1.0       # 0.0 degenerates to plain BC (the paper)
        self.vf_coeff = 1.0
        self.max_weight = 20.0  # clip exp() advantage weights


class MARWIL(BC):
    """Monotonic Advantage Re-Weighted Imitation Learning
    (``rllib/algorithms/marwil``, Wang et al. 2018): behavior cloning
    where each transition's logp is weighted by
    ``exp(beta * normalized_advantage)`` — good trajectories in a mixed
    dataset pull the policy harder than bad ones. Advantages come from
    Monte-Carlo returns (episode boundaries in the dataset) minus a
    jointly-learned value function, normalized by a running second
    moment (the paper's c^2 update). ``beta=0`` reduces exactly to BC.
    """

    _config_cls = MARWILConfig

    @classmethod
    def get_default_config(cls) -> MARWILConfig:
        return MARWILConfig(cls)

    @staticmethod
    def _mc_returns(ds: SampleBatch, gamma: float) -> np.ndarray:
        rews = np.asarray(ds[SampleBatch.REWARDS], np.float64)
        ends = np.asarray(ds[SampleBatch.TERMINATEDS]).astype(bool)
        if SampleBatch.TRUNCATEDS in ds:  # older datasets lack it
            ends = ends | np.asarray(
                ds[SampleBatch.TRUNCATEDS]).astype(bool)
        out = np.zeros_like(rews)
        acc = 0.0
        for i in range(len(rews) - 1, -1, -1):
            if ends[i]:
                acc = 0.0
            acc = rews[i] + gamma * acc
            out[i] = acc
        return out.astype(np.float32)

    def _make_learner(self):
        cfg = self.algo_config
        self.dataset = self._load_dataset()
        self._returns = self._mc_returns(self.dataset, cfg.gamma)
        lw = self.workers.local_worker
        pol = lw.policy
        self._continuous = pol.continuous
        self._rng = np.random.default_rng(cfg.seed)
        params = jax.tree_util.tree_map(jnp.asarray, pol.params)
        optimizer = optax.adam(cfg.lr)
        opt_state = optimizer.init(params)
        continuous = self._continuous
        beta, vf_coeff = cfg.beta, cfg.vf_coeff
        max_w = cfg.max_weight

        def step(params, opt_state, ms, obs, actions, returns):
            def loss_fn(p):
                dist_in, values = _models.actor_critic_apply(p, obs)
                dist = _models.make_distribution(p, dist_in, continuous)
                adv = returns - values
                # running second moment normalizes the exponent
                # (paper's c^2; without it exp() saturates)
                new_ms = 0.99 * ms + 0.01 * jnp.mean(adv ** 2)
                # the normalizer is a running CONSTANT (paper's c^2):
                # gradients through it would teach the critic to game
                # the imitation weight instead of fitting returns
                w = jnp.minimum(
                    jnp.exp(beta * jax.lax.stop_gradient(adv)
                            / jnp.sqrt(jax.lax.stop_gradient(new_ms)
                                       + 1e-8)), max_w)
                pg = -jnp.mean(w * dist.logp(actions))
                vf = jnp.mean(adv ** 2)
                return pg + vf_coeff * 0.5 * vf, (new_ms, pg, vf)

            (loss, (new_ms, pg, vf)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = optimizer.update(grads, opt_state)
            return (optax.apply_updates(params, updates), opt_state,
                    new_ms, pg, vf)

        self._step = jax.jit(step)
        return {"params": params, "opt_state": opt_state,
                "ms": jnp.asarray(1.0)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        n = len(self.dataset)
        pgs, vfs = [], []
        for _ in range(cfg.n_updates_per_iter):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            obs = jnp.asarray(self.dataset[SampleBatch.OBS][idx],
                              jnp.float32)
            act = jnp.asarray(self.dataset[SampleBatch.ACTIONS][idx])
            ret = jnp.asarray(self._returns[idx])
            (self.learner["params"], self.learner["opt_state"],
             self.learner["ms"], pg, vf) = self._step(
                self.learner["params"], self.learner["opt_state"],
                self.learner["ms"], obs, act, ret)
            pgs.append(float(pg))
            vfs.append(float(vf))
        self._timesteps_total += (cfg.n_updates_per_iter
                                  * cfg.train_batch_size)
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner["params"]))
        return {"policy_loss": float(np.mean(pgs)),
                "vf_loss": float(np.mean(vfs)),
                "timesteps_this_iter": cfg.n_updates_per_iter
                * cfg.train_batch_size,
                "dataset_size": n}

    def _learner_state(self):
        return jax.device_get((self.learner["params"],
                               self.learner["opt_state"],
                               self.learner["ms"]))

    def _set_learner_state(self, state):
        if state:
            p, o, ms = state
            self.learner["params"] = jax.tree_util.tree_map(
                jnp.asarray, p)
            self.learner["opt_state"] = jax.tree_util.tree_map(
                jnp.asarray, o)
            self.learner["ms"] = jnp.asarray(ms)


# ---------------------------------------------------------------- CQL


class CQLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.n_updates_per_iter = 32
        self.input_ = None
        self.cql_alpha = 1.0     # conservative penalty weight
        self.tau = 0.005
        self.model = {"fcnet_hiddens": (256, 256)}


class CQL(Algorithm):
    """Conservative Q-Learning for discrete control
    (``rllib/algorithms/cql``): double-Q TD learning on the fixed dataset
    plus the CQL(H) regularizer ``logsumexp Q - Q(s, a_data)``, which
    pushes down out-of-distribution action values so the greedy policy
    stays inside the dataset's support."""

    _config_cls = CQLConfig

    @classmethod
    def get_default_config(cls) -> CQLConfig:
        return CQLConfig(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _make_worker_set(self):
        from ray_tpu.rl.dqn import EpsilonGreedyPolicy
        from ray_tpu.rl.rollout_worker import WorkerSet
        kw = self._worker_kwargs()
        kw["policy_cls"] = EpsilonGreedyPolicy
        return WorkerSet(0, kw)

    def _make_learner(self):
        cfg = self.algo_config
        self.dataset = BC._load_dataset(self)
        lw = self.workers.local_worker
        self._rng = np.random.default_rng(cfg.seed)
        params = jax.tree_util.tree_map(jnp.asarray, lw.get_weights())
        target = jax.tree_util.tree_map(jnp.array, params)
        optimizer = optax.adam(cfg.lr)
        opt_state = optimizer.init(params)
        gamma, alpha, tau = cfg.gamma, cfg.cql_alpha, cfg.tau

        def step(params, target, opt_state, batch):
            obs = batch["obs"]
            act = batch["act"].astype(jnp.int32)
            rew = batch["rew"]
            nxt = batch["nxt"]
            not_done = 1.0 - batch["done"].astype(jnp.float32)

            def loss_fn(p):
                q = _models.mlp_apply(p["pi"], obs, activation="relu")
                qa = jnp.take_along_axis(q, act[:, None], axis=-1)[:, 0]
                qn = _models.mlp_apply(target["pi"], nxt, activation="relu")
                y = rew + gamma * not_done * jax.lax.stop_gradient(
                    jnp.max(qn, axis=-1))
                td = jnp.mean((qa - y) ** 2)
                # CQL(H): minimize logsumexp(Q) (OOD actions) while
                # maximizing Q of dataset actions
                cql = jnp.mean(
                    jax.scipy.special.logsumexp(q, axis=-1) - qa)
                return td + alpha * cql, (td, cql)

            (loss, (td, cql)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            target = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o, target, params)
            return params, target, opt_state, td, cql

        self._step = jax.jit(step, donate_argnums=(0, 1, 2))
        return {"params": params, "target": target, "opt_state": opt_state}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        ds = self.dataset
        n = len(ds)
        tds, cqls = [], []
        for _ in range(cfg.n_updates_per_iter):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {
                "obs": jnp.asarray(ds[SampleBatch.OBS][idx], jnp.float32),
                "act": jnp.asarray(ds[SampleBatch.ACTIONS][idx]),
                "rew": jnp.asarray(ds[SampleBatch.REWARDS][idx],
                                   jnp.float32),
                "nxt": jnp.asarray(ds[SampleBatch.NEXT_OBS][idx],
                                   jnp.float32),
                "done": jnp.asarray(ds[SampleBatch.TERMINATEDS][idx]),
            }
            (self.learner["params"], self.learner["target"],
             self.learner["opt_state"], td, cql) = self._step(
                self.learner["params"], self.learner["target"],
                self.learner["opt_state"], batch)
            tds.append(float(td))
            cqls.append(float(cql))
        self._timesteps_total += cfg.n_updates_per_iter * cfg.train_batch_size
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner["params"]))
        return {"td_loss": float(np.mean(tds)),
                "cql_penalty": float(np.mean(cqls)),
                "timesteps_this_iter": cfg.n_updates_per_iter
                * cfg.train_batch_size,
                "dataset_size": n}

    evaluate = BC.evaluate

    def _learner_state(self):
        return jax.device_get((self.learner["params"],
                               self.learner["target"],
                               self.learner["opt_state"]))

    def _set_learner_state(self, state):
        if state:
            p, t, o = state
            self.learner["params"] = jax.tree_util.tree_map(jnp.asarray, p)
            self.learner["target"] = jax.tree_util.tree_map(jnp.asarray, t)
            self.learner["opt_state"] = jax.tree_util.tree_map(
                jnp.asarray, o)
