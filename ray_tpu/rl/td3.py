"""Twin Delayed DDPG (TD3) for continuous control.

Parity with ``rllib/algorithms/td3`` (DDPG with the three TD3 fixes:
twin critics with min-target, target policy smoothing, delayed policy
updates). Shares SAC's runtime shape (``sac.py``): replay-driven
training with the critic/actor/target updates fused into one jitted
step — the delayed actor update is a ``lax.cond`` inside the program,
not a host-side branch.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.rollout_worker import synchronous_parallel_sample
from ray_tpu.rl.sac import _squash
from ray_tpu.rl.sample_batch import SampleBatch


class DeterministicPolicy(Policy):
    """tanh-squashed deterministic actor with additive Gaussian
    exploration noise (DDPG/TD3 behavior policy)."""

    def __init__(self, spec, config=None, seed: int = 0):
        self.spec = spec
        self.config = dict(config or {})
        if not isinstance(spec.action_space, Box):
            raise ValueError("TD3 requires a continuous (Box) action space")
        self.continuous = True
        obs_dim = int(np.prod(spec.observation_space.shape))
        self.action_dim = int(np.prod(spec.action_space.shape))
        hidden = tuple(self.config.get("fcnet_hiddens", (256, 256)))
        lo = np.broadcast_to(np.asarray(spec.action_space.low,
                                        np.float32).reshape(-1),
                             (self.action_dim,))
        hi = np.broadcast_to(np.asarray(spec.action_space.high,
                                        np.float32).reshape(-1),
                             (self.action_dim,))
        self._scale = jnp.asarray((hi - lo) / 2.0, jnp.float32)
        self._center = jnp.asarray((hi + lo) / 2.0, jnp.float32)
        self.explore_noise = float(self.config.get("explore_noise", 0.1))
        self.params = {"actor": _models.mlp_init(
            jax.random.key(seed), obs_dim, hidden, self.action_dim,
            out_scale=0.01)}
        self._rng = jax.random.key(seed + 1)
        scale, center = self._scale, self._center
        noise_std = self.explore_noise

        def _act(params, rng, obs, explore):
            u = _models.mlp_apply(params["actor"], obs, activation="relu")
            a = _squash(u, scale, center)
            noise = noise_std * scale * jax.random.normal(rng, a.shape)
            lo_b, hi_b = center - scale, center + scale
            noisy = jnp.clip(a + noise, lo_b, hi_b)
            return jnp.where(explore, noisy, a)

        self._act = jax.jit(_act)

    def compute_actions(self, obs, explore: bool = True):
        self._rng, key = jax.random.split(self._rng)
        actions = self._act(self.params, key,
                            jnp.asarray(obs, jnp.float32),
                            jnp.asarray(explore))
        n = len(np.asarray(actions))
        zeros = np.zeros(n, np.float32)
        return np.asarray(actions), zeros, zeros

    def value(self, obs):
        return np.zeros(len(np.asarray(obs)), np.float32)


class TD3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        # original-paper values (lr 1e-3); tau doubled because targets
        # advance only on delayed (every policy_delay-th) steps here
        self.lr = 1e-3
        self.tau = 0.01
        self.policy_delay = 2          # critic steps per actor step
        self.target_noise = 0.2        # target policy smoothing std
        self.target_noise_clip = 0.5
        self.twin_q = True             # False = plain DDPG critic
        self.explore_noise = 0.1
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 500
        self.n_updates_per_iter = 16
        self.rollout_fragment_length = 8
        self.grad_clip = 40.0
        self.model = {"fcnet_hiddens": (256, 256)}


class TD3Learner:
    """Twin critics + delayed deterministic actor, one jitted step."""

    def __init__(self, actor_params, obs_dim: int, action_dim: int,
                 scale: np.ndarray, center: np.ndarray, cfg: TD3Config):
        self.cfg = cfg
        hidden = tuple(cfg.model.get("fcnet_hiddens", (256, 256)))
        kq1, kq2 = jax.random.split(jax.random.key(cfg.seed + 17), 2)
        q_in = obs_dim + action_dim
        self.cparams = {
            "q1": _models.mlp_init(kq1, q_in, hidden, 1, out_scale=1.0),
            "q2": _models.mlp_init(kq2, q_in, hidden, 1, out_scale=1.0),
        }
        self.aparams = {"actor": jax.tree_util.tree_map(
            jnp.asarray, actor_params["actor"])}
        self.target = jax.tree_util.tree_map(
            jnp.array, {**self.cparams, **self.aparams})
        # separate optimizers: the delayed actor update must not advance
        # any optimizer state on critic-only steps (a shared Adam would
        # keep moving the actor on decayed momentum)
        self.critic_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self.actor_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self.copt_state = self.critic_opt.init(self.cparams)
        self.aopt_state = self.actor_opt.init(self.aparams)
        self.rng = jax.random.key(cfg.seed + 5077)
        self._step_count = 0
        gamma, tau = cfg.gamma, cfg.tau
        tn, tn_clip = cfg.target_noise, cfg.target_noise_clip
        scale_a = jnp.asarray(scale, jnp.float32)
        center_a = jnp.asarray(center, jnp.float32)

        def q_apply(qp, obs, act):
            return _models.mlp_apply(
                qp, jnp.concatenate([obs, act], axis=-1),
                activation="relu")[..., 0]

        def actor_apply(ap, obs):
            return _squash(
                _models.mlp_apply(ap, obs, activation="relu"),
                scale_a, center_a)

        def update(cparams, aparams, target, copt, aopt, rng, batch,
                   do_actor: bool):
            # ``do_actor`` is STATIC: two compiled variants — the
            # critic-only one never touches actor params, actor optimizer
            # state, or targets (TD3's delayed update, exactly)
            obs = batch[SampleBatch.OBS]
            acts = batch[SampleBatch.ACTIONS]
            rews = batch[SampleBatch.REWARDS]
            nxt = batch[SampleBatch.NEXT_OBS]
            not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                jnp.float32)
            rng, knoise = jax.random.split(rng)
            # target policy smoothing: clipped noise on the target action
            ta = actor_apply(target["actor"], nxt)
            noise = jnp.clip(
                tn * scale_a * jax.random.normal(knoise, ta.shape),
                -tn_clip * scale_a, tn_clip * scale_a)
            lo_b, hi_b = center_a - scale_a, center_a + scale_a
            ta = jnp.clip(ta + noise, lo_b, hi_b)
            # twin_q is STATIC config: DDPG (twin_q=False) bootstraps
            # and regresses a single critic; TD3 takes the min of twins.
            if cfg.twin_q:
                tq = jnp.minimum(q_apply(target["q1"], nxt, ta),
                                 q_apply(target["q2"], nxt, ta))
            else:
                tq = q_apply(target["q1"], nxt, ta)
            y = rews + gamma * not_done * jax.lax.stop_gradient(tq)

            def critic_loss_fn(cp):
                q1 = q_apply(cp["q1"], obs, acts)
                loss = jnp.mean((q1 - y) ** 2)
                if cfg.twin_q:
                    q2 = q_apply(cp["q2"], obs, acts)
                    loss = loss + jnp.mean((q2 - y) ** 2)
                return loss, jnp.mean(q1)

            (closs, q_mean), cgrads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(cparams)
            cupdates, copt = self.critic_opt.update(cgrads, copt, cparams)
            cparams = optax.apply_updates(cparams, cupdates)
            aloss = jnp.zeros(())
            if do_actor:
                def actor_loss_fn(ap):
                    pi_a = actor_apply(ap["actor"], obs)
                    return -jnp.mean(q_apply(cparams["q1"], obs, pi_a))

                aloss, agrads = jax.value_and_grad(actor_loss_fn)(aparams)
                aupdates, aopt = self.actor_opt.update(agrads, aopt,
                                                       aparams)
                aparams = optax.apply_updates(aparams, aupdates)
                # targets advance only on delayed steps (original TD3)
                target = jax.tree_util.tree_map(
                    lambda t, o: (1 - tau) * t + tau * o, target,
                    {**cparams, **aparams})
            aux = {"critic_loss": closs, "actor_loss": aloss,
                   "q_mean": q_mean}
            return cparams, aparams, target, copt, aopt, rng, aux

        self._update = jax.jit(update, static_argnums=(7,),
                               donate_argnums=(0, 1, 2, 3, 4))
        self._delay = cfg.policy_delay

    def train(self, batch: SampleBatch) -> Dict[str, float]:
        self._step_count += 1
        do_actor = self._step_count % self._delay == 0
        arrays = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()
                  if k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                           SampleBatch.REWARDS, SampleBatch.NEXT_OBS,
                           SampleBatch.TERMINATEDS)}
        (self.cparams, self.aparams, self.target, self.copt_state,
         self.aopt_state, self.rng, aux) = self._update(
            self.cparams, self.aparams, self.target, self.copt_state,
            self.aopt_state, self.rng, arrays, do_actor)
        return {k: float(v) for k, v in aux.items()}

    def actor_weights(self):
        return {"actor": jax.device_get(self.aparams["actor"])}

    def state(self):
        return jax.device_get((self.cparams, self.aparams, self.target,
                               self.copt_state, self.aopt_state,
                               self._step_count))

    def set_state(self, state):
        cp, ap, t, co, ao, c = state
        self.cparams = jax.tree_util.tree_map(jnp.asarray, cp)
        self.aparams = jax.tree_util.tree_map(jnp.asarray, ap)
        self.target = jax.tree_util.tree_map(jnp.asarray, t)
        self.copt_state = jax.tree_util.tree_map(jnp.asarray, co)
        self.aopt_state = jax.tree_util.tree_map(jnp.asarray, ao)
        self._step_count = c


class TD3(Algorithm):
    _config_cls = TD3Config

    @classmethod
    def get_default_config(cls) -> TD3Config:
        return TD3Config(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _worker_kwargs(self):
        kw = super()._worker_kwargs()
        kw["policy_cls"] = DeterministicPolicy
        cfg = dict(kw.get("policy_config") or {})
        cfg.setdefault("explore_noise", self.algo_config.explore_noise)
        kw["policy_config"] = cfg
        return kw

    def _make_learner(self) -> TD3Learner:
        cfg = self.algo_config
        lw = self.workers.local_worker
        spec = lw.get_spec()
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        obs_dim = int(np.prod(spec.observation_space.shape))
        action_dim = int(np.prod(spec.action_space.shape))
        pol = lw.policy
        return TD3Learner(lw.get_weights(), obs_dim, action_dim,
                          np.asarray(pol._scale), np.asarray(pol._center),
                          cfg)

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rl.postprocessing import add_next_obs
        cfg = self.algo_config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(self.workers, max_env_steps=1)
        batch = add_next_obs(batch)
        self.replay.add(batch)
        self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"timesteps_this_iter": len(batch)}
        if (self._timesteps_total
                < cfg.num_steps_sampled_before_learning_starts):
            metrics["learning"] = False
            return metrics
        auxes = []
        for _ in range(cfg.n_updates_per_iter):
            auxes.append(self.learner.train(
                self.replay.sample(cfg.train_batch_size)))
        self.workers.local_worker.set_weights(self.learner.actor_weights())
        metrics.update(learning=True, replay_size=len(self.replay),
                       **{k: float(np.mean([a[k] for a in auxes]))
                          for k in auxes[-1]})
        return metrics

    def _learner_state(self):
        return {"learner": self.learner.state()}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])
