"""Soft Actor-Critic for continuous control.

Parity with ``rllib/algorithms/sac/sac.py`` (training_step: sample ->
replay -> critic/actor/alpha updates -> polyak target sync) and
``sac_torch_policy.py`` (twin soft-Q losses, reparameterized squashed-
Gaussian actor, automatic entropy temperature).

TPU-first learner: critic, actor, and temperature updates plus the polyak
target blend are ONE jitted function over device pytrees — no per-network
optimizer round-trips through the host (the reference runs three separate
torch optimizer steps, ``sac_torch_policy.py`` ``optimizer_fn``).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import Box
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.rollout_worker import synchronous_parallel_sample
from ray_tpu.rl.sample_batch import SampleBatch

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _squash(u: jax.Array, scale: jax.Array, center: jax.Array) -> jax.Array:
    return jnp.tanh(u) * scale + center


def _sample_squashed(actor_params, obs, rng, scale, center):
    """Reparameterized squashed-Gaussian sample -> (action, logp).

    logp includes the tanh change-of-variables correction
    (``sac_torch_policy.py`` SquashedGaussian logp).
    """
    out = _models.mlp_apply(actor_params, obs, activation="relu")
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(rng, mean.shape)
    logp_u = jnp.sum(
        -0.5 * ((u - mean) / std) ** 2 - log_std
        - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
    # d tanh(u)/du = 1 - tanh(u)^2; scaled by the action range
    correction = jnp.sum(
        jnp.log(scale * (1 - jnp.tanh(u) ** 2) + 1e-6), axis=-1)
    return _squash(u, scale, center), logp_u - correction


class SquashedGaussianPolicy(Policy):
    """Tanh-squashed Gaussian actor with state-dependent std.

    Rollout workers hold only the actor; the twin critics live in the
    learner (they never act)."""

    def __init__(self, spec, config=None, seed: int = 0):
        # deliberately not calling Policy.__init__: SAC's parameter layout
        # (actor-only, 2*A outputs) differs from the shared actor-critic
        self.spec = spec
        self.config = dict(config or {})
        if not isinstance(spec.action_space, Box):
            raise ValueError("SAC requires a continuous (Box) action space")
        self.continuous = True
        obs_dim = int(np.prod(spec.observation_space.shape))
        self.action_dim = int(np.prod(spec.action_space.shape))
        hidden = tuple(self.config.get("fcnet_hiddens", (256, 256)))
        # per-dimension bounds: Box.low/high may be scalars or arrays;
        # broadcast to [A] so heterogeneous ranges squash correctly
        lo = np.broadcast_to(np.asarray(spec.action_space.low,
                                        np.float32).reshape(-1),
                             (self.action_dim,))
        hi = np.broadcast_to(np.asarray(spec.action_space.high,
                                        np.float32).reshape(-1),
                             (self.action_dim,))
        self._scale = jnp.asarray((hi - lo) / 2.0, jnp.float32)
        self._center = jnp.asarray((hi + lo) / 2.0, jnp.float32)
        self.params = {"actor": _models.mlp_init(
            jax.random.key(seed), obs_dim, hidden, 2 * self.action_dim,
            out_scale=0.01)}
        self._rng = jax.random.key(seed + 1)
        scale, center = self._scale, self._center

        def _act(params, rng, obs, explore):
            def stochastic():
                a, logp = _sample_squashed(params["actor"], obs, rng,
                                           scale, center)
                return a, logp

            def deterministic():
                out = _models.mlp_apply(params["actor"], obs,
                                        activation="relu")
                mean, _ = jnp.split(out, 2, axis=-1)
                return _squash(mean, scale, center), jnp.zeros(
                    mean.shape[:-1], jnp.float32)

            return jax.lax.cond(explore, stochastic, deterministic)

        self._act = jax.jit(_act)

    def compute_actions(self, obs, explore: bool = True):
        self._rng, key = jax.random.split(self._rng)
        actions, logp = self._act(self.params, key,
                                  jnp.asarray(obs, jnp.float32),
                                  jnp.asarray(explore))
        zeros = np.zeros(len(np.asarray(logp)), np.float32)
        return np.asarray(actions), np.asarray(logp), zeros

    def value(self, obs):  # SAC workers have no value head
        return np.zeros(len(np.asarray(obs)), np.float32)


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4            # shared by actor/critic/alpha
        self.tau = 0.005          # polyak target blend
        self.initial_alpha = 1.0
        self.target_entropy = "auto"   # -action_dim
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 500
        self.n_updates_per_iter = 16
        # fragment > 1: add_next_obs drops the boundary row of each
        # fragment, so a length-1 fragment would yield zero transitions
        self.rollout_fragment_length = 8
        self.grad_clip = 40.0
        self.model = {"fcnet_hiddens": (256, 256)}


class SACLearner:
    """Twin soft-Q + squashed actor + auto temperature, one jitted step."""

    def __init__(self, actor_params, obs_dim: int, action_dim: int,
                 scale: np.ndarray, center: np.ndarray, cfg: SACConfig):
        self.cfg = cfg
        hidden = tuple(cfg.model.get("fcnet_hiddens", (256, 256)))
        kq1, kq2 = jax.random.split(jax.random.key(cfg.seed + 13), 2)
        q_in = obs_dim + action_dim
        self.params = {
            "actor": jax.tree_util.tree_map(
                jnp.asarray, actor_params["actor"]),
            "q1": _models.mlp_init(kq1, q_in, hidden, 1, out_scale=1.0),
            "q2": _models.mlp_init(kq2, q_in, hidden, 1, out_scale=1.0),
            "log_alpha": jnp.asarray(np.log(cfg.initial_alpha), jnp.float32),
        }
        # materialize distinct buffers: the jitted update donates both
        # params and target_q, which must not alias
        self.target_q = jax.tree_util.tree_map(
            jnp.array, {"q1": self.params["q1"], "q2": self.params["q2"]})
        if cfg.target_entropy == "auto":
            self.target_entropy = -float(action_dim)
        else:
            self.target_entropy = float(cfg.target_entropy)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        self.rng = jax.random.key(cfg.seed + 4099)
        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = self.target_entropy
        scale_a = jnp.asarray(scale, jnp.float32)
        center_a = jnp.asarray(center, jnp.float32)

        def q_apply(qp, obs, act):
            return _models.mlp_apply(
                qp, jnp.concatenate([obs, act], axis=-1),
                activation="relu")[..., 0]

        def update(params, target_q, opt_state, rng, batch):
            obs = batch[SampleBatch.OBS]
            acts = batch[SampleBatch.ACTIONS]
            rews = batch[SampleBatch.REWARDS]
            next_obs = batch[SampleBatch.NEXT_OBS]
            not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                jnp.float32)
            rng, k_next, k_pi = jax.random.split(rng, 3)

            # soft target: y = r + gamma (1-d) [min_i tQ_i(s',a') - a logp']
            next_a, next_logp = _sample_squashed(
                params["actor"], next_obs, k_next, scale_a, center_a)
            alpha = jnp.exp(params["log_alpha"])
            tq = jnp.minimum(q_apply(target_q["q1"], next_obs, next_a),
                             q_apply(target_q["q2"], next_obs, next_a))
            y = rews + gamma * not_done * jax.lax.stop_gradient(
                tq - alpha * next_logp)

            def loss_fn(p):
                q1 = q_apply(p["q1"], obs, acts)
                q2 = q_apply(p["q2"], obs, acts)
                critic_loss = (jnp.mean((q1 - y) ** 2)
                               + jnp.mean((q2 - y) ** 2))
                pi_a, pi_logp = _sample_squashed(
                    p["actor"], obs, k_pi, scale_a, center_a)
                # actor maximizes min-Q with entropy bonus; critics are
                # frozen inside this term (stop_gradient) — the joint
                # optimizer step must not let actor gradients leak into Q
                q_pi = jnp.minimum(
                    q_apply(jax.lax.stop_gradient(p["q1"]), obs, pi_a),
                    q_apply(jax.lax.stop_gradient(p["q2"]), obs, pi_a))
                cur_alpha = jax.lax.stop_gradient(jnp.exp(p["log_alpha"]))
                actor_loss = jnp.mean(cur_alpha * pi_logp - q_pi)
                alpha_loss = -p["log_alpha"] * jnp.mean(
                    jax.lax.stop_gradient(pi_logp) + target_entropy)
                total = critic_loss + actor_loss + alpha_loss
                aux = {"critic_loss": critic_loss,
                       "actor_loss": actor_loss,
                       "alpha": jnp.exp(p["log_alpha"]),
                       "entropy": -jnp.mean(pi_logp)}
                return total, aux

            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            target_q = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o,
                target_q, {"q1": params["q1"], "q2": params["q2"]})
            return params, target_q, opt_state, rng, aux

        self._update = jax.jit(update, donate_argnums=(0, 1, 2))

    def train(self, batch: SampleBatch) -> Dict[str, float]:
        arrays = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()
                  if k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                           SampleBatch.REWARDS, SampleBatch.NEXT_OBS,
                           SampleBatch.TERMINATEDS)}
        (self.params, self.target_q, self.opt_state, self.rng,
         aux) = self._update(self.params, self.target_q, self.opt_state,
                             self.rng, arrays)
        return {k: float(v) for k, v in aux.items()}

    def actor_weights(self):
        return {"actor": jax.device_get(self.params["actor"])}

    def state(self):
        return jax.device_get((self.params, self.target_q, self.opt_state))

    def set_state(self, state):
        p, t, o = state
        self.params = jax.tree_util.tree_map(jnp.asarray, p)
        self.target_q = jax.tree_util.tree_map(jnp.asarray, t)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, o)


class SAC(Algorithm):
    _config_cls = SACConfig

    @classmethod
    def get_default_config(cls) -> SACConfig:
        return SACConfig(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _worker_kwargs(self):
        kw = super()._worker_kwargs()
        kw["policy_cls"] = SquashedGaussianPolicy
        return kw

    def _make_learner(self) -> SACLearner:
        cfg = self.algo_config
        lw = self.workers.local_worker
        spec = lw.get_spec()
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        obs_dim = int(np.prod(spec.observation_space.shape))
        action_dim = int(np.prod(spec.action_space.shape))
        pol = lw.policy
        return SACLearner(lw.get_weights(), obs_dim, action_dim,
                          np.asarray(pol._scale), np.asarray(pol._center),
                          cfg)

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rl.postprocessing import add_next_obs
        cfg = self.algo_config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(self.workers, max_env_steps=1)
        batch = add_next_obs(batch)
        self.replay.add(batch)
        self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"timesteps_this_iter": len(batch)}
        if (self._timesteps_total
                < cfg.num_steps_sampled_before_learning_starts):
            metrics["learning"] = False
            return metrics
        auxes = []
        for _ in range(cfg.n_updates_per_iter):
            auxes.append(self.learner.train(
                self.replay.sample(cfg.train_batch_size)))
        self.workers.local_worker.set_weights(self.learner.actor_weights())
        metrics.update(learning=True, replay_size=len(self.replay),
                       **{k: float(np.mean([a[k] for a in auxes]))
                          for k in auxes[-1]})
        return metrics

    def _learner_state(self):
        return {"learner": self.learner.state()}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])
