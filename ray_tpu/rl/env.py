"""Environment API and in-repo classic-control envs.

Parity with the reference's env layer (``rllib/env/``): a Gym-style
``Env`` protocol, ``VectorEnv`` batching, and an env registry
(``rllib/env/env_context.py``, ``ray.tune.registry.register_env``). The
reference depends on external gym; this repo ships its own CartPole and
Pendulum dynamics (numpy for CPU rollout actors) plus a pure-``jax``
functional CartPole for fully on-device rollouts (no reference analogue —
TPU-first addition so the env itself can live under ``jit``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


@dataclass
class Box:
    """Continuous space: shape + bounds (gym.spaces.Box equivalent)."""
    low: float
    high: float
    shape: Tuple[int, ...]
    dtype: Any = np.float32

    def sample(self, rng: np.random.Generator):
        lo = max(self.low, -1e3)
        hi = min(self.high, 1e3)
        return rng.uniform(lo, hi, size=self.shape).astype(self.dtype)

    @property
    def n(self) -> None:
        return None


@dataclass
class Discrete:
    """Discrete space with ``n`` actions (gym.spaces.Discrete equivalent)."""
    n: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return ()

    def sample(self, rng: np.random.Generator):
        return int(rng.integers(self.n))


@dataclass
class EnvSpec:
    observation_space: Box
    action_space: Any  # Box | Discrete
    max_episode_steps: int


class Env:
    """Single-episode environment protocol (gym-style).

    ``reset(seed) -> obs``; ``step(action) -> (obs, reward, terminated,
    truncated, info)``.
    """

    spec: EnvSpec

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool, dict]:
        raise NotImplementedError


class CartPoleEnv(Env):
    """CartPole-v1 dynamics (standard Barto-Sutton-Anderson formulation)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.spec = EnvSpec(
            observation_space=Box(-np.inf, np.inf, (4,)),
            action_space=Discrete(2),
            max_episode_steps=int(config.get("max_episode_steps", 500)),
        )
        self._rng = np.random.default_rng(config.get("seed"))
        self._state = np.zeros(4, np.float32)
        self._t = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._t = 0
        return self._state.copy()

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if int(action) == 1 else -self.FORCE_MAG
        costh, sinth = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pm_len = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pm_len * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * costh ** 2 / total_mass))
        x_acc = temp - pm_len * theta_acc * costh / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._t += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._t >= self.spec.max_episode_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class PendulumEnv(Env):
    """Pendulum-v1 dynamics: continuous torque control, swing-up."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.spec = EnvSpec(
            observation_space=Box(-8.0, 8.0, (3,)),
            action_space=Box(-self.MAX_TORQUE, self.MAX_TORQUE, (1,)),
            max_episode_steps=int(config.get("max_episode_steps", 200)),
        )
        self._rng = np.random.default_rng(config.get("seed"))
        self._theta = 0.0
        self._theta_dot = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._theta), np.sin(self._theta),
                         self._theta_dot], np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta = float(self._rng.uniform(-np.pi, np.pi))
        self._theta_dot = float(self._rng.uniform(-1.0, 1.0))
        self._t = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th, thdot = self._theta, self._theta_dot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.G / (2 * self.L) * np.sin(th)
                         + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + thdot * self.DT
        self._theta, self._theta_dot = th, thdot
        self._t += 1
        truncated = self._t >= self.spec.max_episode_steps
        return self._obs(), -cost, False, truncated, {}


class MemoryCueEnv(Env):
    """Partially observable recall task (the memory-model gate env).

    A binary cue is visible ONLY at the first step; after ``delay``
    blank steps the agent must act to match the cue (+1 reward, else
    -1), then the episode ends. A memoryless policy can do no better
    than 0 expected reward; any working recurrence/attention solves it
    — which makes this the decisive test that ``use_lstm`` /
    ``use_attention`` actually carry information through time.
    Observation: [cue_+1, cue_-1, is_query, t/delay].
    """

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.delay = int(config.get("delay", 3))
        self.spec = EnvSpec(
            observation_space=Box(0.0, 1.0, (4,)),
            action_space=Discrete(2),
            max_episode_steps=self.delay + 2,
        )
        self._rng = np.random.default_rng(config.get("seed"))
        self._cue = 0
        self._t = 0

    def _obs(self) -> np.ndarray:
        o = np.zeros(4, np.float32)
        if self._t == 0:
            o[0 if self._cue == 0 else 1] = 1.0
        if self._t == self.delay + 1:
            o[2] = 1.0  # query flag
        o[3] = self._t / (self.delay + 1)
        return o

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = int(self._rng.integers(2))
        self._t = 0
        return self._obs()

    def step(self, action):
        acted_on_query = self._t == self.delay + 1
        self._t = min(self._t + 1, self.delay + 1)
        if not acted_on_query:
            return self._obs(), 0.0, False, False, {}
        rew = 1.0 if int(action) == self._cue else -1.0
        return self._obs(), rew, True, False, {}


class VectorEnv:
    """Steps ``num_envs`` copies of an env with auto-reset on episode end.

    Reference: ``rllib/env/vector_env.py`` (``VectorEnv.vector_step``).
    Auto-reset semantics: when a sub-env finishes, ``step`` returns the
    *terminal* obs in ``infos[i]["terminal_obs"]`` and the obs array holds
    the freshly reset state (what the next action should condition on).
    """

    def __init__(self, env_maker: Callable[[dict], Env], num_envs: int,
                 config: Optional[dict] = None, seed: Optional[int] = None):
        config = dict(config or {})
        self.envs = []
        for i in range(num_envs):
            c = dict(config)
            if seed is not None:
                c["seed"] = seed + i
            self.envs.append(env_maker(c))
        self.num_envs = num_envs
        self.spec = self.envs[0].spec

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        return np.stack([
            e.reset(None if seed is None else seed + i)
            for i, e in enumerate(self.envs)])

    def step(self, actions):
        obs, rews, terms, truncs, infos = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(a)
            if term or trunc:
                info = dict(info, terminal_obs=o)
                o = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (np.stack(obs), np.array(rews, np.float32),
                np.array(terms), np.array(truncs), infos)


# --------------------------------------------------------------------------
# Pure-JAX CartPole: the whole rollout can live under jit on device.
# --------------------------------------------------------------------------

def jax_cartpole_reset(rng, batch: int):
    """Batched initial states, shape [batch, 4]."""
    import jax
    return jax.random.uniform(rng, (batch, 4), minval=-0.05, maxval=0.05)


def jax_cartpole_step(state, action):
    """One batched CartPole step as a pure function.

    state [B,4] float32, action [B] int32 -> (state', reward [B], done [B]).
    Composable with ``lax.scan`` for on-device rollouts; auto-reset is the
    caller's choice (mask or re-init with fresh rng).
    """
    import jax.numpy as jnp
    x, x_dot, th, th_dot = (state[:, 0], state[:, 1], state[:, 2],
                            state[:, 3])
    force = jnp.where(action == 1, CartPoleEnv.FORCE_MAG,
                      -CartPoleEnv.FORCE_MAG)
    costh, sinth = jnp.cos(th), jnp.sin(th)
    total_mass = CartPoleEnv.CART_MASS + CartPoleEnv.POLE_MASS
    pm_len = CartPoleEnv.POLE_MASS * CartPoleEnv.POLE_HALF_LEN
    temp = (force + pm_len * th_dot ** 2 * sinth) / total_mass
    th_acc = (CartPoleEnv.GRAVITY * sinth - costh * temp) / (
        CartPoleEnv.POLE_HALF_LEN
        * (4.0 / 3.0 - CartPoleEnv.POLE_MASS * costh ** 2 / total_mass))
    x_acc = temp - pm_len * th_acc * costh / total_mass
    tau = CartPoleEnv.TAU
    nxt = jnp.stack([x + tau * x_dot, x_dot + tau * x_acc,
                     th + tau * th_dot, th_dot + tau * th_acc], axis=1)
    done = ((jnp.abs(nxt[:, 0]) > CartPoleEnv.X_LIMIT)
            | (jnp.abs(nxt[:, 2]) > CartPoleEnv.THETA_LIMIT))
    reward = jnp.ones_like(nxt[:, 0])
    return nxt, reward, done


# --------------------------------------------------------------------------
# Registry (reference: ray.tune.registry.register_env)
# --------------------------------------------------------------------------

_ENV_REGISTRY: Dict[str, Callable[[dict], Env]] = {}


def register_env(name: str, maker: Callable[[dict], Env]) -> None:
    _ENV_REGISTRY[name] = maker


def make_env(name_or_maker, config: Optional[dict] = None) -> Env:
    if callable(name_or_maker):
        return name_or_maker(config or {})
    if name_or_maker in _ENV_REGISTRY:
        return _ENV_REGISTRY[name_or_maker](config or {})
    raise KeyError(f"Unknown env {name_or_maker!r}; registered: "
                   f"{sorted(_ENV_REGISTRY)}")


register_env("CartPole-v1", lambda c: CartPoleEnv(c))
register_env("MemoryCue-v0", lambda c: MemoryCueEnv(c))
register_env("Pendulum-v1", lambda c: PendulumEnv(c))
