"""Experience replay.

Parity with ``rllib/utils/replay_buffers/`` (``ReplayBuffer``,
``PrioritizedReplayBuffer`` with sum-tree sampling) in columnar numpy form:
storage is preallocated ring arrays per column, so sampling a training
batch is one fancy-index per column — no per-timestep Python objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring buffer over SampleBatch columns."""

    def __init__(self, capacity: int, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        if not self._cols:
            for k, v in batch.items():
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
        if n > self.capacity:
            batch = batch.slice(n - self.capacity, n)
            n = self.capacity
        end = self._next + n
        for k, v in batch.items():
            if end <= self.capacity:
                self._cols[k][self._next:end] = v
            else:
                split = self.capacity - self._next
                self._cols[k][self._next:] = v[:split]
                self._cols[k][:end - self.capacity] = v[split:]
        self._next = end % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class SumTree:
    """Binary indexed sum tree for O(log n) prefix-sum sampling."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.tree = np.zeros(2 * capacity, np.float64)

    def set(self, idx: np.ndarray, value: np.ndarray) -> None:
        idx = np.atleast_1d(np.asarray(idx)) + self.capacity
        value = np.atleast_1d(np.asarray(value, np.float64))
        for i, v in zip(idx, value):
            delta = v - self.tree[i]
            while i >= 1:
                self.tree[i] += delta
                i //= 2

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idx) + self.capacity]

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def find_prefix(self, prefix: np.ndarray) -> np.ndarray:
        """For each prefix sum, the leaf index where it lands."""
        prefix = np.asarray(prefix, np.float64).copy()
        out = np.zeros(len(prefix), np.int64)
        for j in range(len(prefix)):
            i = 1
            p = prefix[j]
            while i < self.capacity:
                left = 2 * i
                if p <= self.tree[left]:
                    i = left
                else:
                    p -= self.tree[left]
                    i = left + 1
            out[j] = i - self.capacity
        return out


class PrioritizedReplayBuffer(ReplayBuffer):
    """PER (Schaul et al.): P(i) ∝ p_i^alpha, IS weights w_i via beta."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._tree = SumTree(capacity)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = min(len(batch), self.capacity)
        if n == 0:
            return
        start = self._next
        super().add(batch)
        idx = (start + np.arange(n)) % self.capacity
        self._tree.set(idx, np.full(n, self._max_priority ** self.alpha))

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        total = self._tree.total
        prefixes = self._rng.uniform(0, total, num_items)
        idx = self._tree.find_prefix(prefixes)
        idx = np.minimum(idx, self._size - 1)
        probs = self._tree.get(idx) / total
        weights = (self._size * probs) ** (-beta)
        weights = weights / weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._max_priority = max(self._max_priority, float(priorities.max()))
        self._tree.set(idx, priorities ** self.alpha)
