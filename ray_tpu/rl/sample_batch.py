"""Columnar trajectory batches.

Parity with ``rllib/policy/sample_batch.py`` (``SampleBatch``): a dict of
equal-length numpy columns with concat/slice/shuffle/minibatch operations.
Kept as host numpy — batches are assembled on CPU rollout actors and only
cross to the TPU once, as one device_put of the full training batch.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np


class SampleBatch(dict):
    OBS = "obs"
    NEXT_OBS = "new_obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    EPS_ID = "eps_id"
    ACTION_LOGP = "action_logp"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: Optional[np.random.Generator] = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = len(self)
        for s in range(0, n - size + 1, size):
            yield self.slice(s, s + size)

    def split_by_episode(self) -> List["SampleBatch"]:
        if self.EPS_ID not in self:
            return [self]
        ids = self[self.EPS_ID]
        out = []
        start = 0
        for i in range(1, len(ids) + 1):
            if i == len(ids) or ids[i] != ids[start]:
                out.append(self.slice(start, i))
                start = i
        return out

    def pad_to(self, n: int) -> "SampleBatch":
        """Zero-pad every column to length ``n`` (static shapes for XLA)."""
        cur = len(self)
        if cur >= n:
            return self
        pad = n - cur
        return SampleBatch({
            k: np.concatenate(
                [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in self.items()})

    def copy(self) -> "SampleBatch":
        return SampleBatch({k: v.copy() for k, v in self.items()})


def concat_samples(batches: List[SampleBatch]) -> SampleBatch:
    """Reference: ``rllib/policy/sample_batch.py`` ``concat_samples``."""
    batches = [b for b in batches if b is not None and len(b) > 0]
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({
        k: np.concatenate([b[k] for b in batches]) for k in keys})


def batch_to_device(batch: SampleBatch, sharding=None) -> Dict[str, "object"]:
    """One host->device transfer of the whole batch (optionally sharded)."""
    import jax
    arrays = {k: np.asarray(v) for k, v in batch.items()}
    if sharding is None:
        return jax.device_put(arrays)
    return jax.device_put(arrays, sharding)
