"""Recurrent (LSTM) and attention policy models for PPO/IMPALA.

Parity with the reference model catalog's ``use_lstm`` /
``use_attention`` wrappers (``rllib/models/catalog.py:1``,
``torch/recurrent_net.py``, ``torch/attention_net.py`` GTrXL): a memory
core between the observation encoder and the pi/vf heads, enabled by
``model={"use_lstm": True}`` or ``{"use_attention": True}`` on any
algorithm whose worker/learner pair routes through this module (PPO,
IMPALA).

TPU-first shape: BOTH cores are expressed as one ``core_step``
(state [B, S] -> state [B, S]) so sampling is a T=1 step and learning
is a ``lax.scan`` over the SAME function — one compiled program, no
python-side sequence bookkeeping, mid-fragment episode boundaries
handled by a reset mask inside the scan:

- LSTM: state = [h, c] concatenated.
- Attention: state = the rolling window of the last K encoded frames
  (+ a validity flag per slot); each step attends its current frame
  over the window (single head, learned positional embeddings) — the
  fixed-window "transformer-lite" memory the reference's GTrXL
  truncates to in practice.

The fragment contract matches rllib's ``state_in`` + sequence replay:
the rollout worker snapshots per-env state at fragment start
(``rollout_worker.py sample()``), the learner replays each fragment
from that snapshot with in-scan resets at episode ends.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.env import Box, EnvSpec
from ray_tpu.rl.sample_batch import SampleBatch


# ---------------------------------------------------------------------------
# memory cores
# ---------------------------------------------------------------------------

def _branch_init(key: jax.Array, obs_dim: int,
                 config: Dict[str, Any]) -> Tuple[Dict, int, int]:
    """One encoder+core branch -> (params, state_size, out_dim)."""
    use_attn = bool(config.get("use_attention"))
    feat = int(config.get("encoder_dim",
                          config.get("attention_dim", 64) if use_attn
                          else config.get("lstm_cell_size", 64)))
    k_enc, k1, k2, k3, k4 = jax.random.split(key, 5)
    params: Dict[str, Any] = {
        "encoder": _models.mlp_init(k_enc, obs_dim, (), feat,
                                    out_scale=1.0),
    }
    if use_attn:
        K = int(config.get("attention_window", 8))
        d = feat
        params["attn"] = {
            "wq": jax.nn.initializers.orthogonal()(k1, (d, d)),
            "wk": jax.nn.initializers.orthogonal()(k2, (d, d)),
            "wv": jax.nn.initializers.orthogonal()(k3, (d, d)),
            "pos": 0.01 * jax.random.normal(k4, (K, d)),
        }
        return params, K * (d + 1), d  # window + per-slot validity flag
    h = int(config.get("lstm_cell_size", 64))
    params["lstm"] = {
        "wx": jax.nn.initializers.orthogonal()(k1, (feat, 4 * h)),
        "wh": jax.nn.initializers.orthogonal()(k2, (h, 4 * h)),
        # forget-gate bias 1.0 (standard trainability trick)
        "b": jnp.concatenate([jnp.zeros(h), jnp.ones(h),
                              jnp.zeros(2 * h)]),
    }
    return params, 2 * h, h


def memory_model_init(key: jax.Array, obs_dim: int, action_dim: int,
                      config: Dict[str, Any], continuous: bool
                      ) -> Tuple[Dict[str, Any], int]:
    """-> (params, flat state size). ``config`` keys: use_lstm,
    lstm_cell_size, use_attention, attention_window, attention_dim,
    vf_share_layers.

    The value function gets its OWN encoder+core by default
    (``vf_share_layers=False``, the reference PPO default): with a
    shared trunk the value-regression gradient (errors on the scale of
    RETURNS) dwarfs the policy gradient and churns the features under
    the pi head every update — measured on CartPole as a policy pinned
    at random-level return while vf_loss dominated. Untied branches
    double the core but make both objectives independently stable."""
    k_pi_net, k_vf_net, k_pi, k_vf = jax.random.split(key, 4)
    share = bool(config.get("vf_share_layers", False))
    pi_net, s_size, core_out = _branch_init(k_pi_net, obs_dim, config)
    params: Dict[str, Any] = {"pi_net": pi_net}
    state_size = s_size
    if not share:
        vf_net, vs, _ = _branch_init(k_vf_net, obs_dim, config)
        params["vf_net"] = vf_net
        state_size += vs
    params["pi"] = _models.mlp_init(k_pi, core_out, (), action_dim)
    params["vf"] = _models.mlp_init(k_vf, core_out, (), 1, out_scale=1.0)
    if continuous:
        params["log_std"] = jnp.zeros((action_dim,), jnp.float32)
    return params, state_size


def _branch_step(branch, config: Dict[str, Any], obs: jax.Array,
                 state: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One memory step of one branch: obs [B, D] + state [B, S]."""
    feat = jnp.tanh(_models.mlp_apply(branch["encoder"], obs))
    if config.get("use_attention"):
        ap = branch["attn"]
        K, d = ap["pos"].shape
        win = state.reshape(state.shape[0], K, d + 1)
        # roll the window and append the current frame (valid flag 1)
        new_row = jnp.concatenate(
            [feat, jnp.ones(feat.shape[:-1] + (1,))], axis=-1)
        win = jnp.concatenate([win[:, 1:], new_row[:, None]], axis=1)
        frames, valid = win[..., :d], win[..., d]
        q = feat @ ap["wq"]                        # [B, d]
        k = (frames + ap["pos"]) @ ap["wk"]        # [B, K, d]
        v = frames @ ap["wv"]
        att = jnp.einsum("bd,bkd->bk", q, k) / (d ** 0.5)
        att = att + (1.0 - valid) * -1e9           # mask empty slots
        att = jax.nn.softmax(att, axis=-1)
        out = jnp.tanh(feat + jnp.einsum("bk,bkd->bd", att, v))
        return out, win.reshape(state.shape)
    lp = branch["lstm"]
    h_size = lp["wh"].shape[0]
    h, c = state[:, :h_size], state[:, h_size:]
    gates = feat @ lp["wx"] + h @ lp["wh"] + lp["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, jnp.concatenate([h, c], axis=-1)


def _split_state(params, state):
    """[B, S] -> (pi_state, vf_state_or_None), by branch sizes."""
    if "vf_net" not in params:
        return state, None
    half = state.shape[-1] // 2
    return state[..., :half], state[..., half:]


def _core_step(params, config: Dict[str, Any], obs: jax.Array,
               state: jax.Array
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Both branches, one step: -> (pi_out, vf_out, state')."""
    pi_s, vf_s = _split_state(params, state)
    pi_out, pi_s = _branch_step(params["pi_net"], config, obs, pi_s)
    if vf_s is None:
        return pi_out, pi_out, pi_s
    vf_out, vf_s = _branch_step(params["vf_net"], config, obs, vf_s)
    return pi_out, vf_out, jnp.concatenate([pi_s, vf_s], axis=-1)


def memory_forward(params, config, obs_seq: jax.Array, state0: jax.Array,
                   resets: jax.Array
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence replay: obs [B, T, obs], state0 [B, S], resets [B, T]
    (1.0 where a NEW episode starts at step t) -> (dist_in [B, T, A],
    values [B, T], final_state [B, S]). One lax.scan over the shared
    core step; the final state feeds bootstrap-value computation
    (V-trace learners)."""

    def step(state, inputs):
        obs_t, reset_t = inputs                    # [B, D], [B]
        state = state * (1.0 - reset_t)[:, None]
        pi_out, vf_out, state = _core_step(params, config, obs_t, state)
        return state, (pi_out, vf_out)

    final_state, (pi_outs, vf_outs) = jax.lax.scan(
        step, state0,
        (jnp.swapaxes(obs_seq, 0, 1), jnp.swapaxes(resets, 0, 1)))
    pi_outs = jnp.swapaxes(pi_outs, 0, 1)          # [B, T, d]
    vf_outs = jnp.swapaxes(vf_outs, 0, 1)
    dist_in = _models.mlp_apply(params["pi"], pi_outs)
    values = _models.mlp_apply(params["vf"], vf_outs)[..., 0]
    return dist_in, values, final_state


def memory_bootstrap_value(params, config, boot_obs: jax.Array,
                           final_state: jax.Array) -> jax.Array:
    """Value of the post-fragment observation from the fragment-end
    state (fragment-boundary bootstrap for V-trace)."""
    _, vf_out, _ = _core_step(params, config, boot_obs, final_state)
    return _models.mlp_apply(params["vf"], vf_out)[..., 0]


# ---------------------------------------------------------------------------
# sampling-side policy
# ---------------------------------------------------------------------------

class RecurrentPolicy:
    """Stateful sampling policy over a memory core (the model-catalog
    ``use_lstm``/``use_attention`` path). Same surface as ``Policy``
    plus the recurrent-state hooks the rollout worker duck-types."""

    def __init__(self, spec: EnvSpec, config: Optional[dict] = None,
                 seed: int = 0):
        self.spec = spec
        self.config = dict(config or {})
        self.continuous = isinstance(spec.action_space, Box)
        obs_dim = int(np.prod(spec.observation_space.shape))
        self.action_dim = (int(np.prod(spec.action_space.shape))
                           if self.continuous else spec.action_space.n)
        self.params, self.state_size = memory_model_init(
            jax.random.key(seed), obs_dim, self.action_dim, self.config,
            self.continuous)
        self._rng = jax.random.key(seed + 1)
        self._state: Optional[np.ndarray] = None
        continuous = self.continuous
        cfg = self.config

        def _compute(params, rng, obs, state, explore):
            pi_out, vf_out, state = _core_step(params, cfg, obs, state)
            dist_in = _models.mlp_apply(params["pi"], pi_out)
            values = _models.mlp_apply(params["vf"], vf_out)[..., 0]
            dist = _models.make_distribution(params, dist_in, continuous)
            actions = jax.lax.cond(
                explore, lambda: dist.sample(rng),
                lambda: dist.deterministic())
            return actions, dist.logp(actions), values, state

        def _value(params, obs, state):
            _, vf_out, _ = _core_step(params, cfg, obs, state)
            return _models.mlp_apply(params["vf"], vf_out)[..., 0]

        self._compute = jax.jit(_compute)
        self._value = jax.jit(_value)

    def _ensure_state(self, n: int):
        if self._state is None or len(self._state) != n:
            self._state = np.zeros((n, self.state_size), np.float32)

    def compute_actions(self, obs, explore: bool = True):
        obs = jnp.asarray(obs, jnp.float32)
        self._ensure_state(obs.shape[0])
        self._rng, key = jax.random.split(self._rng)
        actions, logp, values, state = self._compute(
            self.params, key, obs, jnp.asarray(self._state),
            jnp.asarray(explore))
        self._state = np.array(state)  # writable copy: reset hooks mutate
        actions = np.asarray(actions)
        if self.continuous:
            actions = np.clip(actions, self.spec.action_space.low,
                              self.spec.action_space.high)
        return actions, np.asarray(logp), np.asarray(values)

    def value(self, obs, env_indices=None) -> np.ndarray:
        """Bootstrap values from the CURRENT state, without advancing it
        (the worker calls this for fragment-end/truncation bootstraps).
        ``env_indices`` selects the state rows when ``obs`` covers only
        a subset of the sub-envs (truncation bootstraps) — without it a
        shape mismatch would silently clobber the whole state."""
        obs = jnp.asarray(obs, jnp.float32)
        if env_indices is not None:
            self._ensure_state(max(env_indices) + 1
                               if self._state is None
                               else len(self._state))
            state = self._state[np.asarray(env_indices, int)]
        else:
            self._ensure_state(obs.shape[0])
            state = self._state
        return np.asarray(self._value(self.params, obs,
                                      jnp.asarray(state)))

    # -- recurrent-state hooks (duck-typed by the rollout worker) --------
    def get_recurrent_state(self, n_envs: int) -> np.ndarray:
        self._ensure_state(n_envs)
        return self._state.copy()

    def on_episode_end(self, env_indices):
        if self._state is not None:
            self._state[np.asarray(env_indices, int)] = 0.0

    # -- weights ---------------------------------------------------------
    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)


# ---------------------------------------------------------------------------
# PPO sequence learner
# ---------------------------------------------------------------------------

class RecurrentPPOLearner:
    """PPO over fragment sequences: minibatches are SEQUENCES, the loss
    replays each from its fragment-start state (rllib's RNN-PPO
    semantics), compiled as scans like ``PPOLearner``."""

    handles_batch_shaping = True  # sequences must not be cut mid-fragment

    def __init__(self, init_params, cfg, continuous: bool,
                 fragment_length: int):
        self.cfg = cfg
        self.T = fragment_length
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr))
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        self.opt_state = self.optimizer.init(self.params)
        self.rng = jax.random.key(cfg.seed + 7919)
        self._continuous = continuous
        self._model_cfg = dict(cfg.model)
        self._train = self._build_train_fn()

    def _build_train_fn(self):
        cfg = self.cfg
        continuous = self._continuous
        model_cfg = self._model_cfg
        optimizer = self.optimizer
        # minibatch size in SEQUENCES
        mb_seqs = max(1, cfg.sgd_minibatch_size // max(1, self.T))

        def loss_fn(params, kl_coeff, batch):
            dist_in, values, _ = memory_forward(
                params, model_cfg, batch[SampleBatch.OBS],
                batch["state_in"], batch["resets"])
            dist = _models.make_distribution(params, dist_in, continuous)
            return _models.ppo_surrogate_loss(dist, values, batch, cfg,
                                              kl_coeff)

        def train_fn(params, opt_state, rng, kl_coeff, batch):
            n_seq = batch[SampleBatch.OBS].shape[0]
            num_mb = max(1, n_seq // mb_seqs)

            def epoch(carry, _):
                params, opt_state, rng = carry
                rng, key = jax.random.split(rng)
                perm = jax.random.permutation(key, n_seq)
                shuffled = jax.tree_util.tree_map(
                    lambda x: x[perm][:num_mb * mb_seqs].reshape(
                        (num_mb, mb_seqs) + x.shape[1:]), batch)

                def mb_step(c, minibatch):
                    p, o = c
                    (_, aux), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(p, kl_coeff, minibatch)
                    updates, o = optimizer.update(grads, o, p)
                    p = optax.apply_updates(p, updates)
                    return (p, o), aux

                (params, opt_state), auxs = jax.lax.scan(
                    mb_step, (params, opt_state), shuffled)
                return (params, opt_state, rng), auxs

            (params, opt_state, rng), auxs = jax.lax.scan(
                epoch, (params, opt_state, rng), None,
                length=cfg.num_sgd_iter)
            metrics = jax.tree_util.tree_map(jnp.mean, auxs)
            metrics["kl"] = jnp.mean(auxs["kl"][-1])
            return params, opt_state, rng, metrics

        return jax.jit(train_fn, donate_argnums=(0, 1))

    def train(self, batch: SampleBatch, kl_coeff: float) -> Dict[str, float]:
        T = self.T
        n = len(batch) // T * T
        n_seq = n // T
        # The minibatch reshape needs at least one full minibatch of
        # sequences; pad small batches by tiling (the sequence analogue
        # of the flat learner's pad_to, which ppo.py skips for us).
        mb_seqs = max(1, self.cfg.sgd_minibatch_size // max(1, T))
        reps = 1 if n_seq >= mb_seqs else -(-mb_seqs // max(1, n_seq))

        def to_seq(v):
            a = np.asarray(v)[:n]
            a = a.reshape((n_seq, T) + a.shape[1:])
            if reps > 1:
                a = np.concatenate([a] * reps)[:mb_seqs]
            return jnp.asarray(a)

        def pad_seqs(a):
            if reps > 1:
                a = np.concatenate([a] * reps)[:mb_seqs]
            return jnp.asarray(a)

        dones = (np.asarray(batch[SampleBatch.TERMINATEDS])[:n]
                 | np.asarray(batch[SampleBatch.TRUNCATEDS])[:n]
                 ).astype(np.float32).reshape(n_seq, T)
        # a NEW episode starts at t where step t-1 ended (never at t=0:
        # the fragment-start state already reflects any prior boundary)
        resets = np.concatenate(
            [np.zeros((n_seq, 1), np.float32), dones[:, :-1]], axis=1)
        arrays = {
            SampleBatch.OBS: to_seq(batch[SampleBatch.OBS]),
            SampleBatch.ACTIONS: to_seq(batch[SampleBatch.ACTIONS]),
            SampleBatch.ACTION_LOGP: to_seq(
                batch[SampleBatch.ACTION_LOGP]),
            SampleBatch.ADVANTAGES: to_seq(batch[SampleBatch.ADVANTAGES]),
            SampleBatch.VALUE_TARGETS: to_seq(
                batch[SampleBatch.VALUE_TARGETS]),
            "state_in": pad_seqs(np.asarray(
                batch["state_in"])[:n].reshape(
                    n_seq, T, -1)[:, 0]),        # fragment-start rows
            "resets": pad_seqs(resets),
        }
        self.params, self.opt_state, self.rng, metrics = self._train(
            self.params, self.opt_state, self.rng,
            jnp.asarray(kl_coeff, jnp.float32), arrays)
        return {k: float(v) for k, v in metrics.items()}

    def state(self):
        return jax.device_get((self.params, self.opt_state))

    def set_state(self, state):
        params, opt_state = state
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)


def uses_memory_model(model_config: Dict[str, Any]) -> bool:
    return bool(model_config.get("use_lstm")
                or model_config.get("use_attention"))
