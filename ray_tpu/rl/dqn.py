"""Deep Q-Networks with replay and target network.

Parity with ``rllib/algorithms/dqn/dqn.py`` (training_step: sample ->
store -> replay-sample -> TD update -> target sync every
``target_network_update_freq``) with double-Q and prioritized replay.
The TD update is one jitted function; the target network is just a second
params pytree on device.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.policy import Policy
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.rollout_worker import synchronous_parallel_sample
from ray_tpu.rl.sample_batch import SampleBatch


class EpsilonGreedyPolicy(Policy):
    """Q-network policy with epsilon-greedy exploration."""

    def __init__(self, spec, config=None, seed: int = 0):
        super().__init__(spec, config, seed)
        if self.continuous:
            raise ValueError("DQN requires a discrete action space")
        self.epsilon = float((config or {}).get("initial_epsilon", 1.0))

        def _q_actions(params, rng, obs, epsilon):
            q = _models.mlp_apply(params["pi"], obs, activation="relu")
            greedy = jnp.argmax(q, axis=-1)
            k1, k2 = jax.random.split(rng)
            rand = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
            explore = jax.random.uniform(k2, greedy.shape) < epsilon
            return jnp.where(explore, rand, greedy), q

        self._q_actions = jax.jit(_q_actions)

    def compute_actions(self, obs, explore: bool = True):
        self._rng, key = jax.random.split(self._rng)
        eps = self.epsilon if explore else 0.0
        actions, q = self._q_actions(
            self.params, key, jnp.asarray(obs, jnp.float32),
            jnp.asarray(eps, jnp.float32))
        actions = np.asarray(actions)
        zeros = np.zeros(len(actions), np.float32)
        return actions, zeros, zeros

    def set_epsilon(self, epsilon: float) -> None:
        self.epsilon = float(epsilon)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 1000
        # Gradient updates between target-net syncs. Too-frequent syncing
        # silently destroys learning (bootstrap chases itself): an ablation
        # on random-policy CartPole replay gives greedy return 9.8 at
        # freq=16 vs 185 at freq=64.
        self.target_network_update_freq = 200
        self.double_q = True
        self.n_updates_per_iter = 8
        self.epsilon_timesteps = 10_000
        self.final_epsilon = 0.02
        self.rollout_fragment_length = 4
        self.grad_clip = 40.0


class DQNLearner:
    def __init__(self, init_params, cfg: DQNConfig):
        self.cfg = cfg
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params)
        self.target_params = self.params
        self.opt_state = self.optimizer.init(self.params)
        gamma, double_q = cfg.gamma, cfg.double_q

        def td_update(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = _models.mlp_apply(p["pi"], batch[SampleBatch.OBS],
                                      activation="relu")
                qa = jnp.take_along_axis(
                    q, batch[SampleBatch.ACTIONS][:, None].astype(jnp.int32),
                    axis=-1)[:, 0]
                q_next_t = _models.mlp_apply(
                    target_params["pi"], batch[SampleBatch.NEXT_OBS],
                    activation="relu")
                if double_q:
                    q_next_o = _models.mlp_apply(
                        p["pi"], batch[SampleBatch.NEXT_OBS],
                        activation="relu")
                    best = jnp.argmax(q_next_o, axis=-1)
                else:
                    best = jnp.argmax(q_next_t, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_t, best[:, None], axis=-1)[:, 0]
                not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                    jnp.float32)
                target = (batch[SampleBatch.REWARDS]
                          + gamma * not_done * jax.lax.stop_gradient(q_next))
                td_error = qa - target
                weights = batch.get("weights",
                                    jnp.ones_like(td_error))
                loss = jnp.mean(weights * optax.huber_loss(qa, target))
                return loss, td_error

            (loss, td_error), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td_error

        self._td_update = jax.jit(td_update)

    def train(self, batch: SampleBatch):
        arrays = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()}
        self.params, self.opt_state, loss, td_error = self._td_update(
            self.params, self.target_params, self.opt_state, arrays)
        return float(loss), np.asarray(td_error)

    def update_target(self) -> None:
        self.target_params = self.params

    def state(self):
        return jax.device_get(
            (self.params, self.target_params, self.opt_state))

    def set_state(self, state):
        p, t, o = state
        self.params = jax.tree_util.tree_map(jnp.asarray, p)
        self.target_params = jax.tree_util.tree_map(jnp.asarray, t)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, o)


class DQN(Algorithm):
    _config_cls = DQNConfig

    @classmethod
    def get_default_config(cls) -> DQNConfig:
        return DQNConfig(cls)

    def _needs_advantages(self) -> bool:
        return False

    def _worker_kwargs(self):
        kw = super()._worker_kwargs()
        kw["policy_cls"] = EpsilonGreedyPolicy
        return kw

    def _make_learner(self) -> DQNLearner:
        cfg = self.algo_config
        self._steps_since_target_sync = 0
        if cfg.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(
                cfg.replay_buffer_capacity, cfg.prioritized_replay_alpha,
                seed=cfg.seed)
        else:
            self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                       seed=cfg.seed)
        return DQNLearner(self.workers.local_worker.get_weights(), cfg)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        self.workers.sync_weights()
        self._update_epsilon()
        batch = synchronous_parallel_sample(self.workers, max_env_steps=1)
        batch = self._with_next_obs(batch)
        self.replay.add(batch)
        self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"timesteps_this_iter": len(batch)}
        if self._timesteps_total < cfg.num_steps_sampled_before_learning_starts:
            metrics["learning"] = False
            return metrics
        losses = []
        for _ in range(cfg.n_updates_per_iter):
            if cfg.prioritized_replay:
                train_batch = self.replay.sample(
                    cfg.train_batch_size, beta=cfg.prioritized_replay_beta)
            else:
                train_batch = self.replay.sample(cfg.train_batch_size)
            loss, td_error = self.learner.train(train_batch)
            if cfg.prioritized_replay:
                self.replay.update_priorities(
                    train_batch["batch_indexes"], td_error)
            losses.append(loss)
            self._steps_since_target_sync += 1
            if self._steps_since_target_sync >= cfg.target_network_update_freq:
                self.learner.update_target()
                self._steps_since_target_sync = 0
        self.workers.local_worker.set_weights(
            jax.device_get(self.learner.params))
        metrics.update(learning=True, mean_td_loss=float(np.mean(losses)),
                       epsilon=self.workers.local_worker.policy.epsilon,
                       replay_size=len(self.replay))
        return metrics

    def _update_epsilon(self) -> None:
        cfg = self.algo_config
        frac = min(1.0, self._timesteps_total / max(1, cfg.epsilon_timesteps))
        eps = 1.0 + frac * (cfg.final_epsilon - 1.0)

        def setter(w, eps=eps):
            w.policy.set_epsilon(eps)

        self.workers.local_worker.policy.set_epsilon(eps)
        if self.workers.remote_workers:
            import ray_tpu
            ray_tpu.get([w.apply.remote(setter)
                         for w in self.workers.remote_workers])

    def _with_next_obs(self, batch: SampleBatch) -> SampleBatch:
        from ray_tpu.rl.postprocessing import add_next_obs
        return add_next_obs(batch)

    def _learner_state(self):
        return {"learner": self.learner.state(),
                "target_sync": self._steps_since_target_sync}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])
            self._steps_since_target_sync = state["target_sync"]
