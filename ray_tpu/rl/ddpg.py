"""Deep Deterministic Policy Gradient.

Parity with ``rllib/algorithms/ddpg`` (Lillicrap et al. 2016). TD3 is
DDPG plus three fixes (twin critics, target smoothing, delayed actor);
this runtime expresses the ancestor the same way APPO is expressed over
IMPALA (``impala.py``): DDPG IS the TD3 machinery configured back to the
original algorithm — single critic (``twin_q=False``), no target-policy
smoothing (``target_noise=0``), actor updated every step
(``policy_delay=1``), per-step soft target updates (tau halved back).
One code path, both papers, same jitted update program.
"""

from __future__ import annotations

from ray_tpu.rl.td3 import TD3, TD3Config


class DDPGConfig(TD3Config):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
        self.tau = 0.005  # per-step soft updates (TD3 doubles for delay)


class DDPG(TD3):
    _config_cls = DDPGConfig

    @classmethod
    def get_default_config(cls) -> DDPGConfig:
        return DDPGConfig(cls)
