"""Policy: parameters + jitted action computation.

Parity with ``rllib/policy/policy.py`` + ``torch_policy.py``
(``compute_actions`` ``torch_policy.py:231``, ``get/set_weights``). The
torch policy's device juggling and tower copies disappear: parameters are
one pytree, action computation is one jitted function, and the learner's
"towers" are a sharding of the same pytree over a mesh (SURVEY §2.5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models as _models
from ray_tpu.rl.env import Box, Discrete, EnvSpec


class Policy:
    """Actor-critic policy over an MLP; subclass for custom networks."""

    def __init__(self, spec: EnvSpec, config: Optional[dict] = None,
                 seed: int = 0):
        self.spec = spec
        self.config = dict(config or {})
        self.continuous = isinstance(spec.action_space, Box)
        obs_dim = int(np.prod(spec.observation_space.shape))
        if self.continuous:
            self.action_dim = int(np.prod(spec.action_space.shape))
        else:
            self.action_dim = spec.action_space.n
        hidden = tuple(self.config.get("fcnet_hiddens", (64, 64)))
        self.params = _models.actor_critic_init(
            jax.random.key(seed), obs_dim, self.action_dim, hidden,
            continuous=self.continuous)
        self._rng = jax.random.key(seed + 1)

        continuous = self.continuous

        def _compute(params, rng, obs, explore):
            dist_inputs, values = _models.actor_critic_apply(params, obs)
            dist = _models.make_distribution(params, dist_inputs, continuous)
            actions = jax.lax.cond(
                explore,
                lambda: dist.sample(rng),
                lambda: dist.deterministic())
            return actions, dist.logp(actions), values

        self._compute = jax.jit(_compute, static_argnames=())

        def _value(params, obs):
            _, values = _models.actor_critic_apply(params, obs)
            return values

        self._value = jax.jit(_value)

    # -- acting ------------------------------------------------------------

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (actions, action_logp, vf_preds), all host numpy."""
        self._rng, key = jax.random.split(self._rng)
        obs = jnp.asarray(obs, jnp.float32)
        actions, logp, values = self._compute(
            self.params, key, obs, jnp.asarray(explore))
        actions = np.asarray(actions)
        if self.continuous:
            lo = self.spec.action_space.low
            hi = self.spec.action_space.high
            actions = np.clip(actions, lo, hi)
        return actions, np.asarray(logp), np.asarray(values)

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(
            self._value(self.params, jnp.asarray(obs, jnp.float32)))

    # -- weights -----------------------------------------------------------

    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
