"""QMIX: monotonic value-function factorization for cooperative MARL.

Parity with ``rllib/algorithms/qmix`` (Rashid et al. 2018): per-agent
utility networks Q_i(obs_i, a_i) combined by a MIXING network whose
weights are produced by hypernetworks conditioned on the global state
and constrained non-negative — so argmax_a Q_tot decomposes into
per-agent argmaxes (the IGM property) while Q_tot can still represent
non-additive team payoffs that defeat VDN.

Runtime shape (this package's DQN family): epsilon-greedy joint
sampling from a ``MultiAgentEnv``, transition replay over JOINT
transitions (all agents' obs/actions + the team reward at one step),
and one jitted update fusing agent nets, hypernets, double-Q targets,
and the periodic target sync. Agents share one utility network with a
one-hot agent id appended to the observation (the reference's default
parameter sharing).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models as _models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env


class QMIXConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or QMIX)
        self.lr = 2e-4
        self.mixing_embed_dim = 16
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 1000
        self.train_batch_size = 128
        self.replay_capacity = 10_000
        self.target_update_freq = 100  # learner steps between syncs
        self.episodes_per_iter = 8
        self.n_updates_per_iter = 16
        self.learning_starts = 200     # joint transitions before updates
        self.model = {"fcnet_hiddens": (64,)}
        self.double_q = True


class QMIXLearner:
    """Shared utility net + monotonic mixer, one jitted update."""

    def __init__(self, obs_dim: int, n_agents: int, n_actions: int,
                 state_dim: int, cfg: QMIXConfig):
        self.cfg = cfg
        embed = cfg.mixing_embed_dim
        hidden = tuple(cfg.model.get("fcnet_hiddens", (64,)))
        ks = jax.random.split(jax.random.key(cfg.seed or 0), 5)
        in_dim = obs_dim + n_agents  # one-hot agent id appended
        self.params = {
            "agent": _models.mlp_init(ks[0], in_dim, hidden, n_actions),
            # hypernetworks: state -> mixer weights (abs() at use site)
            "hyper_w1": _models.mlp_init(ks[1], state_dim, (embed,),
                                         n_agents * embed),
            "hyper_b1": _models.mlp_init(ks[2], state_dim, (), embed),
            "hyper_w2": _models.mlp_init(ks[3], state_dim, (embed,), embed),
            "hyper_v": _models.mlp_init(ks[4], state_dim, (embed,), 1),
        }
        self.target = jax.tree_util.tree_map(jnp.array, self.params)
        self.opt = optax.chain(optax.clip_by_global_norm(10.0),
                               optax.adam(cfg.lr))
        self.opt_state = self.opt.init(self.params)
        self.steps = 0
        gamma = cfg.gamma
        eye = jnp.eye(n_agents)

        def agent_qs(p, obs):
            """obs [B, n_agents, obs_dim] -> [B, n_agents, n_actions]."""
            ids = jnp.broadcast_to(eye, obs.shape[:-2] + eye.shape)
            x = jnp.concatenate([obs, ids], axis=-1)
            return _models.mlp_apply(p["agent"], x, activation="relu")

        def mix(p, qs, state):
            """qs [B, n_agents] + state [B, state_dim] -> Q_tot [B]."""
            w1 = jnp.abs(_models.mlp_apply(p["hyper_w1"], state)
                         ).reshape(state.shape[0], n_agents, embed)
            b1 = _models.mlp_apply(p["hyper_b1"], state)
            h = jax.nn.elu(jnp.einsum("ba,bae->be", qs, w1) + b1)
            w2 = jnp.abs(_models.mlp_apply(p["hyper_w2"], state))
            v = _models.mlp_apply(p["hyper_v"], state)[..., 0]
            return jnp.einsum("be,be->b", h, w2) + v

        def update(params, target, opt_state, batch):
            obs = batch["obs"]            # [B, n_agents, obs_dim]
            acts = batch["actions"]       # [B, n_agents] int
            rews = batch["rewards"]       # [B] team reward
            nxt = batch["next_obs"]
            state = batch["state"]        # [B, state_dim]
            nxt_state = batch["next_state"]
            not_done = 1.0 - batch["dones"].astype(jnp.float32)
            tq_all = agent_qs(target, nxt)
            if cfg.double_q:
                sel = jnp.argmax(agent_qs(params, nxt), axis=-1)
            else:
                sel = jnp.argmax(tq_all, axis=-1)
            tq = jnp.take_along_axis(tq_all, sel[..., None],
                                     axis=-1)[..., 0]
            y = rews + gamma * not_done * jax.lax.stop_gradient(
                mix(target, tq, nxt_state))

            def loss_fn(p):
                q_all = agent_qs(p, obs)
                q = jnp.take_along_axis(q_all, acts[..., None],
                                        axis=-1)[..., 0]
                q_tot = mix(p, q, state)
                return jnp.mean((q_tot - y) ** 2), jnp.mean(q_tot)

            (loss, q_mean), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, "q_tot_mean": q_mean}

        self._update = jax.jit(update, donate_argnums=(0, 2))
        self._agent_qs = jax.jit(lambda p, obs: agent_qs(p, obs))

    def act(self, obs_stack: np.ndarray, epsilon: float,
            rng: np.random.Generator) -> np.ndarray:
        """Greedy per-agent argmax (IGM: joint argmax decomposes) with
        per-agent epsilon exploration. obs_stack [n_agents, obs_dim]."""
        qs = np.asarray(self._agent_qs(self.params, obs_stack[None]))[0]
        greedy = qs.argmax(axis=-1)
        explore = rng.random(len(greedy)) < epsilon
        random_a = rng.integers(0, qs.shape[-1], len(greedy))
        return np.where(explore, random_a, greedy)

    def train(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.steps += 1
        arrays = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.target, self.opt_state, arrays)
        if self.steps % self.cfg.target_update_freq == 0:
            self.target = jax.tree_util.tree_map(jnp.array, self.params)
        return {k: float(v) for k, v in aux.items()}

    def state(self):
        return jax.device_get((self.params, self.target, self.opt_state,
                               self.steps))

    def set_state(self, state):
        p, t, o, s = state
        self.params = jax.tree_util.tree_map(jnp.asarray, p)
        self.target = jax.tree_util.tree_map(jnp.asarray, t)
        self.opt_state = jax.tree_util.tree_map(jnp.asarray, o)
        self.steps = s


class QMIX(Algorithm):
    _config_cls = QMIXConfig

    @classmethod
    def get_default_config(cls) -> QMIXConfig:
        return QMIXConfig(cls)

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if cfg.env is None:
            raise ValueError("AlgorithmConfig.environment(env=...) not set")
        self.env = make_env(cfg.env, dict(cfg.env_config or {}))
        self.agent_ids = tuple(self.env.agent_ids)
        first = self.agent_ids[0]
        self.obs_dim = int(np.prod(
            self.env.observation_spaces[first].shape))
        self.n_actions = int(self.env.action_spaces[first].n)
        self._state_fn = getattr(self.env, "get_state", None)
        if self._state_fn is not None:
            self.state_dim = int(np.prod(self._state_fn().shape))
        else:
            # default global state: concatenation of all agent obs
            self.state_dim = self.obs_dim * len(self.agent_ids)
        self.learner = QMIXLearner(self.obs_dim, len(self.agent_ids),
                                   self.n_actions, self.state_dim, cfg)
        self._replay: List[tuple] = []
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._env_steps = 0

    def _global_state(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        if self._state_fn is not None:
            return np.asarray(self._state_fn(), np.float32).reshape(-1)
        return np.concatenate(
            [np.asarray(obs[a], np.float32).reshape(-1)
             for a in self.agent_ids])

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end
                                           - cfg.epsilon_start)

    def _collect_episode(self) -> float:
        cfg = self.algo_config
        obs = self.env.reset(seed=int(self._rng.integers(1 << 31)))
        total = 0.0
        length = 0
        for _ in range(1000):
            stack = np.stack([np.asarray(obs[a], np.float32).reshape(-1)
                              for a in self.agent_ids])
            acts = self.learner.act(stack, self._epsilon(), self._rng)
            action_dict = {a: int(acts[i])
                           for i, a in enumerate(self.agent_ids)}
            state = self._global_state(obs)
            nxt, rews, terms, truncs, _ = self.env.step(action_dict)
            team_r = float(sum(rews.values())) / len(self.agent_ids)
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            nxt_stack = np.stack(
                [np.asarray(nxt[a], np.float32).reshape(-1)
                 for a in self.agent_ids])
            self._replay.append((stack, acts, team_r, nxt_stack, state,
                                 self._global_state(nxt),
                                 bool(terms.get("__all__"))))
            if len(self._replay) > cfg.replay_capacity:
                del self._replay[: cfg.replay_capacity // 10]
            total += team_r
            length += 1
            self._env_steps += 1
            obs = nxt
            if done:
                break
        self._episode_history.append(
            {"episode_reward": total, "episode_len": length})
        return total

    def _sample_batch(self) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self._replay),
                                 self.algo_config.train_batch_size)
        rows = [self._replay[i] for i in idx]
        return {
            "obs": np.stack([r[0] for r in rows]),
            "actions": np.stack([r[1] for r in rows]).astype(np.int32),
            "rewards": np.asarray([r[2] for r in rows], np.float32),
            "next_obs": np.stack([r[3] for r in rows]),
            "state": np.stack([r[4] for r in rows]),
            "next_state": np.stack([r[5] for r in rows]),
            "dones": np.asarray([r[6] for r in rows], np.float32),
        }

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        before = self._env_steps
        for _ in range(cfg.episodes_per_iter):
            self._collect_episode()
        metrics: Dict[str, Any] = {
            "timesteps_this_iter": self._env_steps - before,
            "epsilon": self._epsilon(),
        }
        self._timesteps_total = self._env_steps
        if len(self._replay) >= cfg.learning_starts:
            auxes = [self.learner.train(self._sample_batch())
                     for _ in range(cfg.n_updates_per_iter)]
            metrics.update({k: float(np.mean([a[k] for a in auxes]))
                            for k in auxes[-1]})
        return metrics

    # self-contained sampling: no worker set
    def step(self) -> Dict[str, Any]:
        import time as _time
        t0 = _time.time()
        result = self.training_step()
        self._episode_history = self._episode_history[-100:]
        rewards = [e["episode_reward"] for e in self._episode_history]
        result["episode_reward_mean"] = float(np.mean(rewards))
        result["episodes_this_iter"] = self.algo_config.episodes_per_iter
        result["timesteps_total"] = self._timesteps_total
        result["sample_throughput"] = (
            result.get("timesteps_this_iter", 0)
            / max(1e-9, _time.time() - t0))
        return result

    def get_weights(self):
        return {"params": jax.device_get(self.learner.params)}

    def set_weights(self, weights):
        self.learner.params = jax.tree_util.tree_map(
            jnp.asarray, weights["params"])

    def _learner_state(self):
        return {"learner": self.learner.state(),
                "env_steps": self._env_steps}

    def _set_learner_state(self, state):
        if state:
            self.learner.set_state(state["learner"])
            self._env_steps = state.get("env_steps", 0)

    def greedy_joint_return(self, episodes: int = 10) -> float:
        """Evaluation: greedy (epsilon=0) episodes, mean team return."""
        totals = []
        for _ in range(episodes):
            obs = self.env.reset(seed=int(self._rng.integers(1 << 31)))
            total = 0.0
            for _ in range(1000):
                stack = np.stack(
                    [np.asarray(obs[a], np.float32).reshape(-1)
                     for a in self.agent_ids])
                acts = self.learner.act(stack, 0.0, self._rng)
                obs, rews, terms, truncs, _ = self.env.step(
                    {a: int(acts[i])
                     for i, a in enumerate(self.agent_ids)})
                total += float(sum(rews.values())) / len(self.agent_ids)
                if terms.get("__all__") or truncs.get("__all__"):
                    break
            totals.append(total)
        return float(np.mean(totals))

    def cleanup(self):
        pass
