"""External environments: the APPLICATION drives the loop.

Parity with ``rllib/env/external_env.py``: instead of the rollout
worker stepping a gym-style env, an external system (a simulator, a web
service, a live process) runs its own loop on its own thread and calls
INTO the policy —

    class MyEnv(ExternalEnv):
        def run(self):
            eid = self.start_episode()
            obs = external_system.reset()
            while True:
                action = self.get_action(eid, obs)
                obs, reward, done = external_system.step(action)
                self.log_returns(eid, reward)
                if done:
                    self.end_episode(eid, obs)
                    eid = self.start_episode()
                    obs = external_system.reset()

Sampling inverts: ``RolloutWorker.sample()`` SERVICES the env's queued
``get_action`` requests with the current policy and drains the logged
experiences into ordinary SampleBatches, so every learner (PPO, IMPALA,
...) trains from an external env unchanged. Off-policy logging
(``log_action``) records actions the external system chose itself.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import EnvSpec
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples


class _Episode:
    def __init__(self, eid: str):
        self.eid = eid
        self.obs: List[np.ndarray] = []
        self.actions: List[Any] = []
        self.logps: List[float] = []
        self.vf_preds: List[float] = []
        self.rewards: List[float] = []  # one slot per action; log_returns
        self.total = 0.0                # adds into the latest slot
        self.length = 0


class ExternalEnv(threading.Thread):
    """Subclass and implement ``run()`` (reference external_env.py:32).

    The thread starts lazily on the worker's first ``sample()``; calls
    block only in ``get_action`` (waiting for the policy's reply).
    """

    def __init__(self, spec: EnvSpec, max_queue: int = 1024):
        super().__init__(daemon=True, name=f"external-env-{id(self):x}")
        self.spec = spec
        self._requests: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._ext_started = False  # NOT _started: Thread owns that name

    # -- the user-facing protocol ---------------------------------------
    def run(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        eid = episode_id or uuid.uuid4().hex
        self._requests.put(("start", eid, None, None))
        return eid

    def get_action(self, episode_id: str, observation) -> Any:
        """Query the current policy; blocks until sample() services it."""
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        self._requests.put(("action", episode_id,
                            np.asarray(observation, np.float32), reply))
        return reply.get()

    def log_action(self, episode_id: str, observation, action) -> None:
        """Record an externally-chosen action (off-policy logging)."""
        self._requests.put(("log_action", episode_id,
                            (np.asarray(observation, np.float32), action),
                            None))

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._requests.put(("reward", episode_id, float(reward), None))

    def end_episode(self, episode_id: str, observation) -> None:
        self._requests.put(("end", episode_id,
                            np.asarray(observation, np.float32), None))


class ExternalEnvSampler:
    """Worker-side half: services the env's request queue with the
    policy and emits SampleBatches shaped exactly like RolloutWorker's
    (per-episode fragments, GAE when requested)."""

    def __init__(self, env: ExternalEnv, policy,
                 fragment_length: int = 200, gamma: float = 0.99,
                 lambda_: float = 0.95, compute_advantages: bool = True):
        self.env = env
        self.policy = policy
        self.fragment_length = fragment_length
        self.gamma = gamma
        self.lambda_ = lambda_
        self.compute_advantages = compute_advantages
        self._episodes: Dict[str, _Episode] = {}
        self._completed_frags: List[SampleBatch] = []
        self._metrics: List[dict] = []
        self._eps_seq = 0

    def _finish_episode(self, ep: _Episode, last_obs, terminated: bool):
        if ep.actions:
            self._completed_frags.append(
                self._to_batch(ep, 0.0 if terminated else float(
                    self.policy.value(np.asarray(last_obs)[None])[0]),
                    terminated))
        self._metrics.append({"episode_reward": ep.total,
                              "episode_len": ep.length})
        self._episodes.pop(ep.eid, None)

    def _to_batch(self, ep: _Episode, bootstrap: float,
                  terminated: bool) -> SampleBatch:
        from ray_tpu.rl.postprocessing import compute_gae
        n = len(ep.actions)
        self._eps_seq += 1
        terms = np.zeros(n, bool)
        terms[-1] = terminated
        truncs = np.zeros(n, bool)
        truncs[-1] = not terminated
        boots = np.zeros(n, np.float32)
        if not terminated:
            # compute_gae's truncated branch reads bootstrap_values[-1];
            # a zero there would silently discard the real bootstrap
            boots[-1] = bootstrap
        frag = SampleBatch({
            SampleBatch.OBS: np.stack(ep.obs[:n]),
            SampleBatch.ACTIONS: np.asarray(ep.actions),
            SampleBatch.REWARDS: np.asarray(ep.rewards, np.float32),
            SampleBatch.TERMINATEDS: terms,
            SampleBatch.TRUNCATEDS: truncs,
            SampleBatch.ACTION_LOGP: np.asarray(ep.logps, np.float32),
            SampleBatch.VF_PREDS: np.asarray(ep.vf_preds, np.float32),
            SampleBatch.EPS_ID: np.full(n, self._eps_seq, np.int64),
            "bootstrap_values": boots,
        })
        if self.compute_advantages:
            compute_gae(frag, bootstrap, self.gamma, self.lambda_)
        else:
            frag["bootstrap_obs"] = np.repeat(
                np.asarray(ep.obs[n - 1])[None], n, 0)
        return frag

    def _handle(self, kind, eid, payload, reply) -> int:
        """Apply one request; returns the number of steps it added."""
        ep = self._episodes.get(eid)
        if kind == "start":
            self._episodes[eid] = _Episode(eid)
        elif kind == "action":
            if ep is None:
                ep = self._episodes[eid] = _Episode(eid)
            a, logp, vf = self.policy.compute_actions(payload[None])
            ep.obs.append(payload)
            ep.actions.append(a[0])
            ep.logps.append(float(logp[0]))
            ep.vf_preds.append(float(vf[0]))
            ep.rewards.append(0.0)  # log_returns fills it in
            ep.length += 1
            reply.put(a[0])
            return 1
        elif kind == "log_action":
            if ep is None:
                ep = self._episodes[eid] = _Episode(eid)
            obs, action = payload
            ep.obs.append(obs)
            ep.actions.append(action)
            ep.logps.append(0.0)
            ep.vf_preds.append(0.0)
            ep.rewards.append(0.0)
            ep.length += 1
            return 1
        elif kind == "reward":
            if ep is not None:
                # total always counts; the per-step slot only when one is
                # open (a reward racing a fragment boundary keeps the
                # metric right even though its step already shipped)
                ep.total += payload
                if ep.rewards:
                    ep.rewards[-1] += payload
        elif kind == "end":
            if ep is not None:
                self._finish_episode(ep, payload, terminated=True)
        return 0

    def sample(self) -> SampleBatch:
        """Service requests until fragment_length steps are drained."""
        import queue as _q
        if not self.env._ext_started:
            self.env._ext_started = True
            self.env.start()
        steps = 0
        while steps < self.fragment_length:
            try:
                item = self.env._requests.get(timeout=5.0)
            except _q.Empty:
                if not self.env.is_alive() and self.env._ext_started:
                    break  # finite external app: return what we have
                continue
            steps += self._handle(*item)
        # Drain already-queued trailing events (the rewards/episode-ends
        # belonging to the steps just collected) without blocking.
        while True:
            try:
                item = self.env._requests.get_nowait()
            except _q.Empty:
                break
            steps += self._handle(*item)
        out: List[SampleBatch] = list(self._completed_frags)
        self._completed_frags = []
        # open episodes contribute their collected prefix (truncated
        # fragment bootstrapped from the policy's value at the last obs)
        for ep in list(self._episodes.values()):
            if ep.actions:
                out.append(self._to_batch(
                    ep, float(self.policy.value(
                        np.asarray(ep.obs[-1])[None])[0]),
                    terminated=False))
                # keep the episode open but drop consumed transitions
                fresh = _Episode(ep.eid)
                fresh.total = ep.total
                fresh.length = ep.length
                self._episodes[ep.eid] = fresh
        return concat_samples(out) if out else SampleBatch({
            SampleBatch.OBS: np.zeros((0, 1), np.float32)})

    def pop_metrics(self) -> List[dict]:
        out, self._metrics = self._metrics, []
        return out
