"""In-process multi-node cluster for tests.

Parity with ``python/ray/cluster_utils.py:99`` (``Cluster.add_node`` :165):
spin up N virtual nodes under one runtime so multi-node scheduling, placement
groups, spilling, and failure handling run in CI without real hosts — the
same role the reference's Cluster plays for multi-raylet tests.
"""

from __future__ import annotations
import logging

import os
from typing import Dict, Optional

from ray_tpu._private import worker as _worker
from ray_tpu._private.resources import CPU, TPU, ResourceSet

logger = logging.getLogger("ray_tpu")


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self._worker = _worker.init(_create_default_node=False,
                                    ignore_reinit_error=False)
        self._nodes = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def runtime(self):
        return self._worker.runtime

    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 **kwargs):
        amounts: Dict[str, float] = {
            CPU: num_cpus if num_cpus is not None else float(os.cpu_count() or 1)}
        if num_tpus:
            amounts[TPU] = num_tpus
        if resources:
            amounts.update(resources)
        node = self.runtime.add_node(ResourceSet(amounts))
        self._nodes.append(node)
        return node

    def remove_node(self, node):
        self.runtime.remove_node(node.node_id)

    def shutdown(self):
        _worker.shutdown()


class ProcessCluster:
    """Real multi-process cluster for tests: one C++ state-service process
    plus N host-daemon processes, each a separate OS process speaking the
    wire protocol — the process-level analogue the reference gets from
    ``Cluster`` starting real raylets (``python/ray/cluster_utils.py:99``).

    Usage::

        cluster = ProcessCluster(num_daemons=2, num_cpus=2)
        ray_tpu.init(address=cluster.address)
        ...
        cluster.kill_daemon(0)      # chaos: SIGKILL a host
        cluster.shutdown()
    """

    def __init__(self, num_daemons: int = 2, num_cpus: float = 2,
                 resources: Optional[Dict[str, float]] = None,
                 data_dir: str = "", heartbeat_timeout_ms: float = 3000,
                 daemon_heartbeat_s: float = 0.5,
                 tp_cpu_devices: int = 0,
                 daemon_env: Optional[Dict[str, str]] = None):
        """``tp_cpu_devices`` > 0 gives every daemon that many virtual CPU
        JAX devices and enables Gloo collectives, so tensor-plane tests can
        run compiled cross-process collectives without TPUs (see
        collective/tensor_plane.py).

        ``daemon_env`` is merged into EVERY daemon's environment —
        including replacements the autoscaler's node provider launches
        later — so a cluster-wide chaos schedule (``RAY_TPU_CHAOS``
        preemption storm) keeps firing on gang-replaced nodes instead of
        silently ending with the first casualty."""
        import subprocess
        import sys
        import tempfile
        import time as _time
        from ray_tpu._private.state_client import start_state_service
        self._subprocess = subprocess
        self._data_dir = data_dir
        self._heartbeat_timeout_ms = heartbeat_timeout_ms
        self.state_proc, self.address = start_state_service(
            data_dir=data_dir, heartbeat_timeout_ms=heartbeat_timeout_ms)
        self.daemons = []
        self._daemon_args = dict(num_cpus=num_cpus,
                                 resources=resources or {},
                                 heartbeat_s=daemon_heartbeat_s,
                                 tp_cpu_devices=tp_cpu_devices,
                                 env=dict(daemon_env or {}))
        for _ in range(num_daemons):
            self.add_daemon()

    def node_provider(self, node_types: Dict[str, Dict[str, float]]
                      ) -> "ProcessClusterNodeProvider":
        """An autoscaler NodeProvider whose "cloud" is THIS cluster:
        create_node spawns a real daemon process (the multi-process
        analogue of the reference's fake_multi_node provider)."""
        return ProcessClusterNodeProvider(self, node_types)

    def restart_state_service(self):
        """SIGKILL the state service and restart it on the SAME port
        (journal-recovered when ``data_dir`` was set) — the GCS
        fault-tolerance chaos scenario: daemons and drivers must
        reconnect and re-register, not wedge."""
        from ray_tpu._private.state_client import start_state_service
        port = int(self.address.rsplit(":", 1)[1])
        if self.state_proc.poll() is None:
            self.state_proc.kill()
            self.state_proc.wait(timeout=10)
        self.state_proc, addr = start_state_service(
            port=port, data_dir=self._data_dir,
            heartbeat_timeout_ms=self._heartbeat_timeout_ms)
        assert addr == self.address, (addr, self.address)

    def add_daemon(self, num_cpus: Optional[float] = None,
                   resources: Optional[Dict[str, float]] = None,
                   num_tpus: float = 0,
                   env: Optional[Dict[str, str]] = None,
                   labels: Optional[Dict[str, str]] = None):
        from ray_tpu._private.node import spawn_daemon
        extra = dict(env or {})  # e.g. RAY_TPU_CHAOS / flight-recorder knobs
        env = ({} if os.environ.get("JAX_PLATFORMS")
               else {"JAX_PLATFORMS": "cpu"})  # test daemons stay CPU
        env.update(self._daemon_args.get("env") or {})  # cluster-wide
        env.update(extra)
        proc, addr = spawn_daemon(
            self.address,
            num_cpus=(num_cpus if num_cpus is not None
                      else self._daemon_args["num_cpus"]),
            num_tpus=num_tpus,
            resources=resources or self._daemon_args["resources"],
            heartbeat_s=self._daemon_args["heartbeat_s"],
            tp_cpu_devices=self._daemon_args.get("tp_cpu_devices") or 0,
            labels=labels,
            env_overrides=env)
        self.daemons.append({"proc": proc, "address": addr})
        return addr

    def kill_daemon(self, index: int):
        """SIGKILL a host daemon (chaos testing — no graceful teardown)."""
        import signal as _signal
        d = self.daemons[index]
        if d["proc"].poll() is None:
            d["proc"].send_signal(_signal.SIGKILL)
            d["proc"].wait(timeout=10)

    def shutdown(self):
        for d in self.daemons:
            if d["proc"].poll() is None:
                d["proc"].terminate()
        for d in self.daemons:
            try:
                d["proc"].wait(timeout=10)
            except Exception as e:
                logger.debug("daemon stop timed out; killing: %s", e)
                d["proc"].kill()
        if self.state_proc.poll() is None:
            self.state_proc.terminate()
            try:
                self.state_proc.wait(timeout=10)
            except Exception as e:
                logger.debug("state service stop timed out; killing: %s", e)
                self.state_proc.kill()


class ProcessClusterNodeProvider:
    """Autoscaler NodeProvider over a live ``ProcessCluster``: launching
    a node spawns a real host-daemon PROCESS that registers with the
    state service (the reference's ``fake_multi_node`` provider, at
    process rather than in-process granularity). Lets the autoscaler
    loop drive an actual multi-process cluster in tests."""

    def __init__(self, cluster: "ProcessCluster",
                 node_types: Dict[str, Dict[str, float]]):
        import threading
        self._cluster = cluster
        self._node_types = dict(node_types)
        # the autoscaler's monitor thread drives this concurrently with
        # the test thread: all map access is locked (FakeNodeProvider
        # does the same)
        self._lock = threading.Lock()
        self._nodes: Dict[str, int] = {}   # provider id -> daemon index
        self._types: Dict[str, str] = {}
        self._addrs: Dict[str, str] = {}   # provider id -> daemon address
        self._node_ids: Dict[str, object] = {}  # provider id -> NodeID

    def non_terminated_nodes(self):
        with self._lock:
            items = list(self._nodes.items())
        return [pid for pid, idx in items
                if self._cluster.daemons[idx]["proc"].poll() is None]

    def create_node(self, node_type: str, count: int = 1):
        import uuid as _uuid
        if node_type not in self._node_types:
            raise ValueError(f"unknown node type {node_type!r}")
        res = dict(self._node_types[node_type])
        created = []
        for _ in range(count):
            cpus = res.get("CPU", 1)
            extra = {k: v for k, v in res.items()
                     if k not in ("CPU", "TPU")}
            with self._lock:
                # The type label rides on the daemon so hazard journaling
                # (distributed.begin_drain) and per-type rate estimation
                # can attribute preemptions to the node type that had them.
                addr = self._cluster.add_daemon(
                    num_cpus=cpus, resources=extra,
                    num_tpus=res.get("TPU", 0),
                    labels={"autoscaler-node-type": node_type})
                idx = next(i for i, d in enumerate(self._cluster.daemons)
                           if d["address"] == addr)
                pid = f"proc-{node_type}-{_uuid.uuid4().hex[:6]}"
                self._nodes[pid] = idx
                self._types[pid] = node_type
                self._addrs[pid] = addr
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str):
        with self._lock:
            idx = self._nodes.pop(provider_node_id, None)
            self._types.pop(provider_node_id, None)
            self._addrs.pop(provider_node_id, None)
            self._node_ids.pop(provider_node_id, None)
        if idx is not None:
            self._cluster.kill_daemon(idx)

    def node_resources(self, provider_node_id: str):
        with self._lock:
            t = self._types.get(provider_node_id)
        return dict(self._node_types.get(t, {}))

    def node_type(self, provider_node_id: str) -> str:
        with self._lock:
            return self._types[provider_node_id]

    def runtime_node_id(self, provider_node_id: str):
        """Runtime NodeID of the daemon (resolved from the state service
        by address) — _scale_down matches it against node utilization to
        find idle nodes; without it scale-down would silently no-op."""
        with self._lock:
            cached = self._node_ids.get(provider_node_id)
            addr = self._addrs.get(provider_node_id)
        if cached is not None:
            return cached
        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.state_client import StateClient
        state = StateClient(self._cluster.address)
        try:
            for info in state.list_nodes():
                if info.address == addr:
                    nid = NodeID(info.node_id)
                    with self._lock:
                        self._node_ids[provider_node_id] = nid
                    return nid
        finally:
            state.close()
        raise KeyError(provider_node_id)
