"""In-process multi-node cluster for tests.

Parity with ``python/ray/cluster_utils.py:99`` (``Cluster.add_node`` :165):
spin up N virtual nodes under one runtime so multi-node scheduling, placement
groups, spilling, and failure handling run in CI without real hosts — the
same role the reference's Cluster plays for multi-raylet tests.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ray_tpu._private import worker as _worker
from ray_tpu._private.resources import CPU, TPU, ResourceSet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self._worker = _worker.init(_create_default_node=False,
                                    ignore_reinit_error=False)
        self._nodes = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def runtime(self):
        return self._worker.runtime

    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 **kwargs):
        amounts: Dict[str, float] = {
            CPU: num_cpus if num_cpus is not None else float(os.cpu_count() or 1)}
        if num_tpus:
            amounts[TPU] = num_tpus
        if resources:
            amounts.update(resources)
        node = self.runtime.add_node(ResourceSet(amounts))
        self._nodes.append(node)
        return node

    def remove_node(self, node):
        self.runtime.remove_node(node.node_id)

    def shutdown(self):
        _worker.shutdown()
