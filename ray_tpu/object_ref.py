"""Distributed futures.

Parity with the reference's ``ObjectRef`` (``python/ray/includes/object_ref.pxi``,
owner info in ``src/ray/core_worker/reference_count.h:61``): a handle to an
immutable value that may not exist yet. Refs are awaitable, hashable, and
participate in reference counting — when the last local ref drops, the value
may be freed unless lineage pinning keeps it for reconstruction.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner=None):
        self._id = object_id
        self._owner = owner
        if owner is not None:
            owner.reference_counter.add_local_ref(object_id)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def task_id(self):
        return self._id.task_id()

    def future(self):
        """Return a concurrent.futures.Future resolved with the value."""
        import concurrent.futures

        from ray_tpu._private import worker as _worker
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_worker.get(self))
            except BaseException as e:  # noqa: BLE001 - propagate task errors
                fut.set_exception(e)

        _worker.global_worker().runtime.offload(_resolve)
        return fut

    def __await__(self):
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        # Borrowing protocol (reference_count.h:61): the owning runtime
        # decides the reduction. In-process it pins until the deserializer
        # re-binds; the distributed runtime instead emits a marker carrying
        # owner/sender addresses so the deserializer can register a borrow
        # with the owner (see DistributedRuntime.reduce_ref).
        if self._owner is not None:
            return self._owner.reduce_ref(self._id)
        return (_deserialize_ref, (self._id.binary(),))

    def __del__(self):
        owner = getattr(self, "_owner", None)
        if owner is not None:
            try:
                owner.reference_counter.remove_local_ref(self._id)
            except Exception:  # raylint: allow(swallow) interpreter teardown: owner runtime may be gone
                pass


def _deserialize_ref(id_bytes: bytes) -> "ObjectRef":
    from ray_tpu._private import worker as _worker
    oid = ObjectID(id_bytes)
    runtime = _worker.try_global_runtime()
    if runtime is not None:
        return ObjectRef(oid, owner=runtime)
    return ObjectRef(oid, owner=None)


def _deserialize_borrowed_ref(id_bytes: bytes) -> "ObjectRef":
    from ray_tpu._private import worker as _worker
    oid = ObjectID(id_bytes)
    runtime = _worker.try_global_runtime()
    if runtime is not None:
        ref = ObjectRef(oid, owner=runtime)  # takes a local ref first
        runtime.reference_counter.unpin_for_task(oid)  # then release the pin
        return ref
    return ObjectRef(oid, owner=None)
