"""Checkpoint: dict / directory / object-ref interconvertible.

Parity with ``python/ray/air/checkpoint.py:42``. TPU-native notes: array
leaves are stored via Orbax (async-friendly, multi-host-aware) when a
directory form is requested; the dict form keeps ``jax.Array`` leaves
device-resident (zero-copy through the object store).
"""

from __future__ import annotations
import logging

import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional

logger = logging.getLogger("ray_tpu")


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None,
                 manifest: Optional[Any] = None):
        if sum(x is not None for x in (data, directory, manifest)) != 1:
            raise ValueError(
                "provide exactly one of data=, directory= or manifest=")
        self._data = data
        self._directory = directory
        # A ray_tpu.checkpoint.CheckpointRef: the checkpoint lives in a
        # content-addressed engine store; this object is a light, picklable
        # pointer and loads lazily (elastic restore reshards at load time).
        self._manifest = manifest

    # -- constructors ---------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_manifest(cls, root: str,
                      manifest_name: Optional[str] = None) -> "Checkpoint":
        """Checkpoint backed by a committed engine manifest. With no
        ``manifest_name`` the newest complete commit is pinned now, so the
        reference stays stable under later saves."""
        from ray_tpu.checkpoint import (CheckpointNotFound, CheckpointRef,
                                        resolve_latest)
        name = manifest_name or resolve_latest(root)
        if name is None:
            raise CheckpointNotFound(f"no committed checkpoint under {root}")
        return cls(manifest=CheckpointRef(root, name))

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        from ray_tpu._private import worker as _worker
        return cls.from_dict(_worker.get(ref))

    # -- conversions ----------------------------------------------------------

    @property
    def manifest_ref(self):
        """The engine CheckpointRef backing this checkpoint, or None."""
        return self._manifest

    def to_dict(self) -> Dict[str, Any]:
        if self._manifest is not None:
            from ray_tpu.train import session as _session
            s = _session._get_session()
            if s is not None:
                # inside a train worker: restore THIS rank's (resharded)
                # slice of the saved world
                return self._manifest.load(rank=s.world_rank,
                                           world_size=s.world_size)
            return self._manifest.load()
        if self._data is not None:
            # Copy the dict *containers* recursively so caller mutation of
            # any nesting level cannot corrupt the stored checkpoint. Leaves
            # (jax arrays are immutable) are shared, not copied.
            return _copy_containers(self._data)
        return self._load_directory(self._directory)

    def to_directory(self, path: Optional[str] = None) -> str:
        if self._directory is not None and path is None:
            return self._directory
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        data = self.to_dict()
        arrays = {}
        plain = {}
        for k, v in data.items():
            if _is_array_tree(v):
                arrays[k] = v
            else:
                plain[k] = v
        if arrays:
            self._save_arrays(os.path.join(path, "arrays"), arrays)
        with open(os.path.join(path, "data.pkl"), "wb") as f:
            import cloudpickle
            cloudpickle.dump(plain, f)
        return path

    def to_object_ref(self):
        from ray_tpu._private import worker as _worker
        return _worker.put(self.to_dict())

    # -- orbax-backed array io ------------------------------------------------

    @staticmethod
    def _save_arrays(path: str, arrays: Dict[str, Any]):
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            if os.path.exists(path):
                shutil.rmtree(path)
            ckptr.save(os.path.abspath(path), arrays)
        except Exception as e:
            logger.debug("orbax save failed; using pickle fallback: %s", e)
            # Fallback: host-side pickle of numpy-fied leaves. Remove any
            # partially-written orbax dir first — _load_directory prefers
            # the directory form, so a corrupt one would shadow the pickle.
            if os.path.exists(path):
                shutil.rmtree(path, ignore_errors=True)
            import jax
            import numpy as np
            host = jax.tree.map(lambda x: np.asarray(x), arrays)
            with open(path + ".pkl", "wb") as f:
                pickle.dump(host, f)

    @staticmethod
    def _load_directory(path: str) -> Dict[str, Any]:
        data: Dict[str, Any] = {}
        pkl = os.path.join(path, "data.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                data.update(pickle.load(f))
        arrays_path = os.path.join(path, "arrays")
        if os.path.exists(arrays_path):
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            data.update(ckptr.restore(os.path.abspath(arrays_path)))
        elif os.path.exists(arrays_path + ".pkl"):
            with open(arrays_path + ".pkl", "rb") as f:
                data.update(pickle.load(f))
        return data


def _copy_containers(v: Any) -> Any:
    if isinstance(v, dict):
        return {k: _copy_containers(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_containers(x) for x in v]
    return v


def _is_array_tree(v: Any) -> bool:
    """True if v is an array or a pytree whose leaves are all arrays."""
    import jax
    import numpy as np
    leaves = jax.tree.leaves(v)
    if not leaves:
        return False
    return all(isinstance(l, (jax.Array, np.ndarray)) for l in leaves)
