"""Predictors: checkpoint -> inference callable.

Parity with ``python/ray/air`` predictors (``train/predictor.py``
``Predictor.from_checkpoint/predict``, framework predictors) and
``BatchPredictor`` (``python/ray/train/batch_predictor.py``): scaled
offline inference over a Dataset. TPU-first: a ``JaxPredictor`` holds a
jitted apply over a params pytree, optionally sharded over a mesh — the
"model per GPU actor" of the reference becomes "one compiled program per
host, batch sharded over the mesh's data axis".
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base predictor (``predict`` over numpy batches)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, data) -> Any:
        if self.preprocessor is not None:
            data = self.preprocessor.transform_batch(data)
        return self._predict(data)

    def _predict(self, data) -> Any:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """apply_fn(params, batch) jitted once; params live on device.

    ``from_checkpoint`` expects the checkpoint dict layout the Train
    layer writes: ``{"params": pytree, ...}``.
    """

    def __init__(self, params: Any, apply_fn: Callable[[Any, Any], Any],
                 preprocessor=None, sharding=None):
        super().__init__(preprocessor)
        import jax
        if sharding is not None:
            params = jax.device_put(params, sharding)
        self.params = params
        self._apply = jax.jit(apply_fn)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable[[Any, Any], Any],
                        preprocessor=None, sharding=None) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params", data)
        return cls(params, apply_fn, preprocessor=preprocessor,
                   sharding=sharding)

    def _predict(self, data):
        import jax.numpy as jnp
        if isinstance(data, dict):
            data = {k: jnp.asarray(np.asarray(v)) for k, v in data.items()}
        else:
            data = jnp.asarray(np.asarray(data))
        return np.asarray(self._apply(self.params, data))


class BatchPredictor:
    """Dataset-scale inference (``batch_predictor.py``): the predictor is
    constructed once per pool worker from the checkpoint and reused for
    every batch that worker maps."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                feature_columns=None, keep_columns=None,
                prediction_column: str = "predictions"):
        checkpoint = self.checkpoint
        predictor_cls = self.predictor_cls
        predictor_kwargs = self.predictor_kwargs
        cache: Dict[str, Predictor] = {}

        def infer(batch):
            # One predictor per worker process/thread, built lazily
            # (reference: per-actor model load in BatchPredictor).
            p = cache.get("p")
            if p is None:
                p = predictor_cls.from_checkpoint(checkpoint,
                                                  **predictor_kwargs)
                cache["p"] = p
            if feature_columns and isinstance(batch, dict):
                features = {c: batch[c] for c in feature_columns}
            else:
                features = batch
            preds = p.predict(features)
            out = {}
            if keep_columns and isinstance(batch, dict):
                for c in keep_columns:
                    out[c] = batch[c]
            out[prediction_column] = np.asarray(preds)
            return out

        return dataset.map_batches(infer, batch_size=batch_size,
                                   batch_format="numpy")
