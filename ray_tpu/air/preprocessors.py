"""Dataset preprocessors.

Parity with ``python/ray/data/preprocessors/`` (StandardScaler,
MinMaxScaler, LabelEncoder, OneHotEncoder, SimpleImputer, Chain;
base class ``ray/data/preprocessor.py``): fit on a Dataset, transform
Datasets or batches. Fitted state is plain numpy so a preprocessor
travels inside a Checkpoint to serving (``air/checkpoint.py`` flow).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Preprocessor:
    """fit/transform over dict-of-columns batches (arrow-block analogue)."""

    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self.fit_required():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return dataset.map_batches(self.transform_batch,
                                   batch_format="numpy")

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def fit_required(self) -> bool:
        return True

    # subclass API
    def _fit(self, dataset) -> None:
        raise NotImplementedError

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


def _column_arrays(dataset, columns: List[str]) -> Dict[str, np.ndarray]:
    cols: Dict[str, List[np.ndarray]] = {c: [] for c in columns}
    for batch in dataset.iter_batches(batch_format="numpy"):
        for c in columns:
            cols[c].append(np.asarray(batch[c]))
    return {c: np.concatenate(v) for c, v in cols.items()}


class StandardScaler(Preprocessor):
    """Zero-mean unit-variance per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        arrays = _column_arrays(dataset, self.columns)
        self.stats_ = {
            c: (float(v.mean()), float(v.std()) or 1.0)
            for c, v in arrays.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (mean, std) in self.stats_.items():
            out[c] = (np.asarray(batch[c]) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        arrays = _column_arrays(dataset, self.columns)
        self.stats_ = {
            c: (float(v.min()), float(v.max()))
            for c, v in arrays.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (lo, hi) in self.stats_.items():
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c]) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """String/any labels -> dense int codes."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Dict[Any, int] = {}

    def _fit(self, dataset):
        values = _column_arrays(dataset, [self.label_column])[
            self.label_column]
        self.classes_ = {v: i for i, v in
                         enumerate(sorted(set(values.tolist())))}

    def transform_batch(self, batch):
        out = dict(batch)
        out[self.label_column] = np.array(
            [self.classes_[v] for v in
             np.asarray(batch[self.label_column]).tolist()],
            dtype=np.int64)
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, Dict[Any, int]] = {}

    def _fit(self, dataset):
        arrays = _column_arrays(dataset, self.columns)
        self.categories_ = {
            c: {v: i for i, v in enumerate(sorted(set(a.tolist())))}
            for c, a in arrays.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, cats in self.categories_.items():
            values = np.asarray(batch[c]).tolist()
            onehot = np.zeros((len(values), len(cats)), np.float32)
            for i, v in enumerate(values):
                idx = cats.get(v)
                if idx is not None:
                    onehot[i, idx] = 1.0
            out.pop(c)
            out[f"{c}_onehot"] = onehot
        return out


class SimpleImputer(Preprocessor):
    """NaNs -> mean (numeric columns)."""

    def __init__(self, columns: List[str], strategy: str = "mean"):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unsupported strategy {strategy!r}")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = 0.0
        self.stats_: Dict[str, float] = {}

    def _fit(self, dataset):
        arrays = _column_arrays(dataset, self.columns)
        for c, v in arrays.items():
            self.stats_[c] = (float(np.nanmean(v))
                              if self.strategy == "mean"
                              else self.fill_value)

    def transform_batch(self, batch):
        out = dict(batch)
        for c, fill in self.stats_.items():
            v = np.asarray(batch[c], np.float64).copy()
            v[np.isnan(v)] = fill
            out[c] = v
        return out


class Chain(Preprocessor):
    """Sequential composition; fit runs left to right on the running
    transform (reference: ``preprocessors/chain.py``)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, dataset) -> "Chain":
        ds = dataset
        for p in self.preprocessors:
            p.fit(ds)
            ds = p.transform(ds)
        self._fitted = True
        return self

    def _fit(self, dataset):  # pragma: no cover — fit() overridden
        raise AssertionError

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch


class BatchMapper(Preprocessor):
    """Stateless user function as a preprocessor."""

    def __init__(self, fn):
        self.fn = fn
        self._fitted = True

    def fit_required(self) -> bool:
        return False

    def _fit(self, dataset):
        pass

    def transform_batch(self, batch):
        return self.fn(batch)
