"""AIR-style configs.

Parity with ``python/ray/air/config.py`` (``ScalingConfig``, ``RunConfig``,
``FailureConfig``) adapted to TPU: ``use_tpu`` + ``topology`` replace
``use_gpu``; workers map 1:1 to TPU hosts (the device-owner process model).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Union


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-8" custom resource label

    def worker_resources(self) -> Dict[str, float]:
        r = dict(self.resources_per_worker or {})
        r.setdefault("CPU", 1)
        if self.use_tpu:
            r.setdefault("TPU", 1)
        if self.topology:
            r.setdefault(self.topology, 1)
        return r


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = unlimited restarts


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    # int: save every Nth reported checkpoint (0/1 = every one).
    # "auto": risk-tuned cadence — the session solves the Young–Daly
    # interval from the fleet preemption hazard and measured step /
    # checkpoint costs (ray_tpu.checkpoint.cadence), re-tuning as the
    # hazard estimate moves.
    checkpoint_frequency: Union[int, str] = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # tune.SyncConfig: mirror the experiment dir to durable storage
    sync_config: Optional[Any] = None


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Any]
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None
