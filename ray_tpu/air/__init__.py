from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, Result,
                                RunConfig, ScalingConfig)

__all__ = ["Checkpoint", "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result"]
