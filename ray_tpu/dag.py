"""Lazy task/actor DAGs.

Parity with ``python/ray/dag/`` (``dag_node.py``, ``function_node.py``,
``class_node.py``): ``.bind()`` builds a graph, ``.execute()`` materializes it
by submitting the underlying tasks/actors. Used by Serve graphs and Workflows.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DAGNode:
    def __init__(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve_deps(self, executed: Dict[int, Any]):
        def resolve(v):
            if isinstance(v, DAGNode):
                key = id(v)
                if key not in executed:
                    executed[key] = v._execute_impl(executed)
                return executed[key]
            return v
        args = tuple(resolve(a) for a in self._bound_args)
        kwargs = {k: resolve(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def execute(self, *exec_args):
        executed: Dict[Any, Any] = {}
        if exec_args:
            executed["__input__"] = exec_args[0] if len(exec_args) == 1 else exec_args
        return self._execute_impl(executed, exec_args)

    def _execute_impl(self, executed, exec_args=()):
        raise NotImplementedError

    def get_other_args_to_resolve(self):
        return {}


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, executed, exec_args=()):
        args, kwargs = self._resolve_deps(executed)
        return self._remote_fn.remote(*args, **kwargs)


class InputNode(DAGNode):
    """Placeholder for the DAG's runtime input (reference: dag/input_node.py)."""

    _current: List["InputNode"] = []

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        InputNode._current.append(self)
        return self

    def __exit__(self, *a):
        InputNode._current.pop()

    def _execute_impl(self, executed, exec_args=()):
        return executed.get("__input__")


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _execute_impl(self, executed, exec_args=()):
        args, kwargs = self._resolve_deps(executed)
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs):
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node, method_name, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _execute_impl(self, executed, exec_args=()):
        key = id(self._class_node)
        if key not in executed:
            executed[key] = self._class_node._execute_impl(executed)
        handle = executed[key]
        args, kwargs = self._resolve_deps(executed)
        return getattr(handle, self._method_name).remote(*args, **kwargs)
