"""``python -m ray_tpu.doctor`` — see :mod:`ray_tpu.doctor`."""

import sys

from ray_tpu.doctor import main

if __name__ == "__main__":
    sys.exit(main())
