"""Cluster health doctor: collect crash forensics, diagnose, explain.

``python -m ray_tpu.doctor`` is the post-mortem / triage entry point on
top of the always-on flight recorder (:mod:`ray_tpu.observability.
recorder`) and the dashboard's forensics federation:

1. **collect** — seal orphaned recordings on this machine (processes that
   died without running their hooks), inventory the local flight dir, and
   — when ``--address`` points at a live state service — pull every alive
   daemon's thread stacks, in-flight tasks, bundle inventory, metric
   snapshots and merged timeline through the same NODE_DEBUG fan-out the
   dashboard head serves.
2. **diagnose** — correlate: sealed bundles become crash reports carrying
   the in-flight trace_id, last spans/log/chaos lines and breaker/
   heartbeat state at death; ``heartbeat_consecutive_misses > 0`` plus
   live stacks flags a hang; cross-process task-span outliers flag
   stragglers; hosts the head could not reach are called out.
3. **render** — human-readable diagnosis, or ``--json`` for machines.

The doctor holds no state and never needs the cluster to be healthy: with
no ``--address`` it still reads (and seals) whatever the dead processes
left on disk.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["collect", "diagnose", "explain_knob", "render_explain",
           "render_text", "main"]


def collect(flight_dir: Optional[str] = None,
            address: Optional[str] = None,
            seal: bool = True) -> dict:
    """Gather everything diagnosable. Local disk always; cluster-wide
    live state only when ``address`` (state-service host:port) is given.
    Collection never raises for a sick cluster — per-source errors land
    in ``errors`` and diagnosis runs on what was reachable."""
    from ray_tpu.observability import recorder as _flight
    out: Dict[str, Any] = {"ts": time.time(), "errors": []}
    sealed_now: List[str] = []
    if seal:
        try:
            sealed_now = _flight.seal_orphans(root=flight_dir,
                                              sealed_by="doctor")
        except Exception as e:  # noqa: BLE001
            out["errors"].append(f"seal_orphans: {e!r}")
    out["sealed_now"] = sealed_now
    try:
        out["local"] = _flight.disk_report(root=flight_dir)
    except Exception as e:  # noqa: BLE001
        out["errors"].append(f"disk_report: {e!r}")
        out["local"] = {"root": flight_dir or "", "recordings": [],
                        "bundles": []}
    out["cluster"] = None
    if address:
        from ray_tpu.dashboard.head import DashboardHead
        head = DashboardHead(address)  # API methods only; never start()ed
        try:
            cluster: Dict[str, Any] = {}
            for key, fetch in (
                    ("nodes", head._cluster),
                    ("forensics", head._forensics),
                    ("timeline", head._timeline)):
                try:
                    cluster[key] = fetch()
                except Exception as e:  # noqa: BLE001
                    out["errors"].append(f"{key}: {e!r}")
                    cluster[key] = None
            try:
                snaps, missing = head._metric_snapshots()
                cluster["metrics"] = {"snapshots": snaps,
                                      "missing_hosts": missing}
            except Exception as e:  # noqa: BLE001
                out["errors"].append(f"metrics: {e!r}")
                cluster["metrics"] = None
            try:
                cluster["drain"] = _drain_progress(head.state)
            except Exception as e:  # noqa: BLE001
                out["errors"].append(f"drain: {e!r}")
                cluster["drain"] = None
            try:
                cluster["preempt"] = _preempt_signals(head.state)
            except Exception as e:  # noqa: BLE001
                out["errors"].append(f"preempt: {e!r}")
                cluster["preempt"] = None
            try:
                cluster["autopilot"] = _autopilot_journal(head.state)
            except Exception as e:  # noqa: BLE001
                out["errors"].append(f"autopilot: {e!r}")
                cluster["autopilot"] = None
            out["cluster"] = cluster
        finally:
            head.stop()
    return out


def _drain_progress(state) -> Dict[str, dict]:
    """Per-node migration progress published by drain orchestrators into
    the state-service KV (namespace ``drain``, key ``progress:<node_id>``):
    phase, tasks still pending, actors checkpointed, objects migrated."""
    progress: Dict[str, dict] = {}
    for key in state.kv_keys(prefix=b"progress:", namespace=b"drain"):
        val = state.kv_get(key, namespace=b"drain")
        if not val:
            continue
        try:
            progress[key[len(b"progress:"):].hex()] = json.loads(val)
        except (ValueError, UnicodeDecodeError):
            continue
    return progress


def _preempt_signals(state) -> Dict[str, Any]:
    """Preemption-plane health from the state KV (``preempt`` namespace,
    autoscaler/hazard.py layout): per-node consecutive probe failures
    published by each host daemon's watcher, and the hazard estimator's
    last published fleet rate."""
    from ray_tpu.autoscaler import hazard as _hazard
    probes: Dict[str, int] = {}
    for key in state.kv_keys(prefix=_hazard.PROBE_PREFIX,
                             namespace=_hazard.NAMESPACE):
        val = state.kv_get(key, namespace=_hazard.NAMESPACE)
        if not val:
            continue
        try:
            probes[key[len(_hazard.PROBE_PREFIX):].decode()] = int(
                json.loads(val).get("failures") or 0)
        except (ValueError, UnicodeDecodeError):
            continue
    return {"probe_failures": probes,
            "fleet_rate_per_hour": _hazard.read_fleet_rate(state)}


def _autopilot_journal(state) -> Dict[str, Any]:
    """The autopilot's decision journal replayed from the state KV
    (``autopilot`` namespace, journal.py layout): every knob change the
    controller made, with the evidence snapshot, guardrail bounds and
    old->new values it journaled at decision time.  This is what
    ``--explain <knob>`` renders."""
    from ray_tpu._private.config import _config
    from ray_tpu.autopilot import journal as _journal
    records = _journal.read_from_state(state)
    window_s = float(_config.get("autopilot_flap_window_s"))
    return {
        "decisions": records,
        "flapping": _journal.flap_counts(records, window_s),
        "flap_window_s": window_s,
    }


def _node_states(collected: dict) -> Dict[str, str]:
    """node_id(hex) -> lifecycle state, from the live cluster view
    (empty when collection ran disk-only)."""
    states: Dict[str, str] = {}
    cluster = collected.get("cluster") or {}
    for n in ((cluster.get("nodes") or {}).get("nodes") or []):
        nid = n.get("node_id", "")
        states[nid] = (n.get("state")
                       or ("ALIVE" if n.get("alive") else "DEAD"))
    return states


def _all_bundles(collected: dict) -> List[dict]:
    """Every sealed bundle the collection saw, deduped: the local disk
    report plus each daemon's NODE_DEBUG ``include_bundles`` payload
    (which on a single test machine usually point at the same dirs)."""
    seen = set()
    bundles: List[dict] = []

    def add(b: dict):
        key = (b.get("dir") or "", b.get("pid"), b.get("sealed_ts"))
        if key in seen:
            return
        seen.add(key)
        bundles.append(b)

    for b in (collected.get("local") or {}).get("bundles") or []:
        add(b)
    cluster = collected.get("cluster") or {}
    forensics = cluster.get("forensics") or {}
    for payload in (forensics.get("nodes") or {}).values():
        for b in ((payload.get("forensics") or {}).get("bundles") or []):
            add(b)
    return bundles


def _crash_reports(bundles: List[dict]) -> List[dict]:
    reports = []
    for b in bundles:
        if b.get("clean"):
            continue
        inflight = b.get("inflight") or {}
        chaos_tail = b.get("chaos") or []
        state = b.get("state") or {}
        reports.append({
            "role": b.get("role", "?"),
            "label": b.get("label", ""),
            "pid": b.get("pid"),
            "dir": b.get("dir", ""),
            "exit_reason": b.get("exit_reason", "?"),
            "sealed_by": b.get("sealed_by", "?"),
            "sealed_ts": b.get("sealed_ts"),
            "trace_ids": b.get("trace_ids") or [],
            "inflight_tasks": [
                {"task_id": tid, "name": t.get("name", "?"),
                 "trace_id": t.get("trace_id", "")}
                for tid, t in sorted(inflight.items())],
            "chaos_spec": b.get("chaos_spec", ""),
            "chaos_points_fired": chaos_tail[-8:],
            "heartbeat_misses": state.get("heartbeat_misses"),
            "last_logs": (b.get("logs") or [])[-5:],
            "last_spans": [s.get("name") for s in
                           (b.get("spans") or [])[-5:]],
            "exception": (b.get("exception") or {}).get("type", ""),
            "faulthandler": bool(b.get("faulthandler")),
        })
    reports.sort(key=lambda r: r.get("sealed_ts") or 0)
    return reports


def _hang_reports(collected: dict) -> List[dict]:
    """Heartbeat-miss-triggered hang detection: any node whose
    ``heartbeat_consecutive_misses`` gauge is nonzero is sampled — its
    live thread stacks (already in the forensics fan-out) say where it
    is stuck. A DRAINING node missing heartbeats is NOT a hang — it is
    mid-migration and about to decommission — so those entries are
    tagged ``expected`` and excluded from the issue count."""
    cluster = collected.get("cluster") or {}
    metrics = cluster.get("metrics") or {}
    snaps = metrics.get("snapshots") or {}
    forensics = cluster.get("forensics") or {}
    nodes = forensics.get("nodes") or {}
    states = _node_states(collected)
    hangs = []
    for src, families in snaps.items():
        for fam in families or []:
            if fam.get("name") != "heartbeat_consecutive_misses":
                continue
            for _name, tags, value in fam.get("samples") or []:
                if not value or value <= 0:
                    continue
                node_tag = dict(tags).get("node", src)
                stacks = {}
                inflight = {}
                for nid, payload in nodes.items():
                    if nid.startswith(node_tag) or \
                            node_tag.startswith(nid[:8]):
                        stacks = payload.get("stacks") or {}
                        inflight = payload.get("inflight") or {}
                        break
                node_state = ""
                for nid, st in states.items():
                    if nid.startswith(node_tag) or \
                            node_tag.startswith(nid[:8]):
                        node_state = st
                        break
                hangs.append({"node": node_tag, "source": src,
                              "consecutive_misses": value,
                              "expected": node_state == "DRAINING",
                              "node_state": node_state,
                              "inflight_tasks": sorted(
                                  t.get("name", "?")
                                  for t in inflight.values()),
                              "stacks": stacks})
    return hangs


def _straggler_reports(collected: dict,
                       factor: float = 3.0) -> List[dict]:
    """Cross-process step-time outliers: group completed task spans by
    name across ``pid`` rows of the merged timeline; a process whose
    mean duration exceeds ``factor`` × the cluster median (≥3 samples,
    ≥2 processes) is a straggler."""
    cluster = collected.get("cluster") or {}
    timeline = cluster.get("timeline") or {}
    events = timeline.get("traceEvents") or []
    by_name: Dict[str, Dict[str, List[float]]] = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") != "task":
            continue
        dur = ev.get("dur")
        if not dur:
            continue
        by_name.setdefault(ev.get("name", "?"), {}) \
            .setdefault(str(ev.get("pid", "?")), []).append(float(dur))
    out = []
    for name, per_pid in by_name.items():
        durs = [d for ds in per_pid.values() for d in ds]
        if len(durs) < 3 or len(per_pid) < 2:
            continue
        median = statistics.median(durs)
        if median <= 0:
            continue
        for pid, ds in per_pid.items():
            mean = sum(ds) / len(ds)
            if mean > factor * median:
                out.append({"task": name, "process": pid,
                            "mean_us": round(mean, 1),
                            "cluster_median_us": round(median, 1),
                            "slowdown": round(mean / median, 1),
                            "samples": len(ds)})
    out.sort(key=lambda r: -r["slowdown"])
    return out


def _perf_reports(collected: dict,
                  baseline: Optional[dict] = None) -> dict:
    """Perf-plane section: cluster-merged latency quantiles per
    histogram, recovered from the raw bucket counts riding the metric
    snapshots already collected — no extra wire round trip.

    ``baseline`` (the ``--perf-baseline`` JSON: ``{hist_name:
    {"p99_ms": budget, ..., "tolerance": 1.5}}``) turns the section
    into an SLO regression gate: any current quantile above
    ``budget * tolerance`` is a drift finding (counted as an issue)."""
    from ray_tpu.observability import perf as perf_mod
    cluster = collected.get("cluster") or {}
    snaps = (cluster.get("metrics") or {}).get("snapshots") or {}
    agg: Dict[str, dict] = {}
    for families in snaps.values():
        for name, p in perf_mod.extract_perf(families or []).items():
            a = agg.setdefault(name, {"counts": [], "sum_ms": 0.0,
                                      "bounds": p.get("bounds")})
            a["counts"] = perf_mod.merge_counts(
                [a["counts"], [int(c) for c in p["counts"]]])
            a["sum_ms"] += float(p.get("sum_ms", 0.0))
    summaries = {name: perf_mod.summarize(a["counts"], a["sum_ms"],
                                          a["bounds"])
                 for name, a in sorted(agg.items())}
    drift = []
    for name, budgets in (baseline or {}).items():
        current = summaries.get(name)
        if current is None:
            continue
        tolerance = float(budgets.get("tolerance", 1.5))
        for key, base in budgets.items():
            if key == "tolerance" or key not in current:
                continue
            got = current[key]
            if got > float(base) * tolerance:
                drift.append({"hist": name, "metric": key,
                              "got_ms": round(got, 3),
                              "baseline_ms": float(base),
                              "tolerance": tolerance})
    return {"cluster": summaries, "drift": drift}


def _goodput_reports(collected: dict,
                     baseline: Optional[dict] = None) -> dict:
    """Goodput-ledger section: per-job wall-clock attribution merged
    across every node's ``"goodput"`` payload riding the already-
    collected metric snapshots.

    ``baseline`` (the ``--goodput-baseline`` JSON: ``{job: {"goodput_pct":
    floor, "restart_downtime_s": ceiling, "tolerance": 1.0}}``) turns the
    section into an efficiency-SLO gate: ``*_pct`` budgets are floors
    (goodput below ``floor * tolerance`` is a drift finding), ``*_s``
    budgets are ceilings on that category's merged seconds (above
    ``ceiling * tolerance`` drifts).  Both count as issues."""
    from ray_tpu.observability import goodput as goodput_mod
    cluster = collected.get("cluster") or {}
    snaps = (cluster.get("metrics") or {}).get("snapshots") or {}
    payloads = []
    for families in snaps.values():
        p = goodput_mod.extract_goodput(families or [])
        if p:
            payloads.append(p)
    jobs = goodput_mod.merge_payloads(payloads)
    drift = []
    for job, budgets in (baseline or {}).items():
        rec = jobs.get(job)
        if rec is None:
            continue
        tolerance = float(budgets.get("tolerance", 1.0))
        for key, base in budgets.items():
            if key == "tolerance":
                continue
            if key.endswith("_pct"):
                got = float(rec.get("goodput_pct", 0.0))
                if got < float(base) * tolerance:
                    drift.append({"job": job, "metric": key,
                                  "got_pct": round(got, 2),
                                  "baseline_pct": float(base),
                                  "tolerance": tolerance})
            elif key.endswith("_s"):
                cat = key[:-2]
                got = float((rec.get("cats") or {}).get(cat, 0.0))
                if got > float(base) * tolerance:
                    drift.append({"job": job, "metric": key,
                                  "got_s": round(got, 3),
                                  "baseline_s": float(base),
                                  "tolerance": tolerance})
    return {"jobs": jobs, "drift": drift}


def _manifest_drift(groups: dict, manifest: Optional[dict],
                    tolerance: float = 1.0) -> List[dict]:
    """Cross-check the runtime collective ledger against the static plan
    raylint's R29 emits (``comms_manifest.json``).

    Every ledgered group/op with a nonzero count must appear in the
    manifest's ``groups`` table, either under its own group name or under
    the ``"*"`` wildcard (statically-unresolvable group names) —
    otherwise it is an *unplanned* collective and reports as drift.  For
    planned ops, a ``wire_ratio_max`` ceiling in the manifest entry gates
    the ledgered wire/logical ratio, and the predicted per-link bytes
    (ledger wire bytes x the shared busbw formula for the group's world
    size) ride along informationally on the entry.  Reused by
    ``_comms_reports`` (the ``__manifest__`` baseline key), the devtools
    tests, and run_sanitizers.sh's manifest-vs-ledger gate."""
    from ray_tpu.observability import comms as comms_mod
    drift: List[dict] = []
    plan = (manifest or {}).get("groups") or {}
    wildcard = plan.get("*") or {}
    for gname, rec in sorted((groups or {}).items()):
        planned = dict(wildcard)
        planned.update(plan.get(gname) or {})
        world = int(rec.get("world_size") or 0)
        for op, o in sorted((rec.get("ops") or {}).items()):
            count = int(o.get("count") or 0)
            if count <= 0:
                continue
            ent = planned.get(op)
            if ent is None:
                drift.append({"group": gname,
                              "metric": f"{op}_unplanned",
                              "got": count, "baseline": 0.0,
                              "tolerance": tolerance})
                continue
            nbytes = float(o.get("bytes") or 0.0)
            wire = float(o.get("wire_bytes", nbytes) or nbytes)
            factor_fn = comms_mod._BUSBW.get(op, lambda n: 1.0)
            ent["predicted_link_bytes"] = round(wire * factor_fn(world), 1)
            ratio_max = ent.get("wire_ratio_max")
            if ratio_max is not None and nbytes:
                got = wire / nbytes
                if got > float(ratio_max) * tolerance:
                    drift.append({"group": gname,
                                  "metric": f"{op}_wire_ratio",
                                  "got_ratio": round(got, 4),
                                  "baseline_ratio": float(ratio_max),
                                  "tolerance": tolerance})
    return drift


def _comms_reports(collected: dict, baseline: Optional[dict] = None,
                   factor: float = 3.0) -> dict:
    """Comms-plane section: every node's ``"comms"`` payload (collective
    op ledger, per-rank arrival-skew histograms, link matrix) merged
    exactly, then attributed — ``skew_flags`` names a laggard rank whose
    p95 arrival skew is >= ``factor`` x the median of its peers,
    ``link_flags`` names a peer link with failovers or an outlier GB/s.

    ``baseline`` (the ``--comms-baseline`` JSON: ``{group: {"<op>_gbps":
    floor, "skew_p95_ms": ceiling, "mismatches": ceiling,
    "tolerance": 1.0}}``) turns the section into a bandwidth/skew SLO
    gate: ``*_gbps`` budgets are floors on the merged algorithm
    bandwidth (the ``allreduce_f32_gbps``-style gate the quantized-
    collective roadmap item compares against), ``skew_p95_ms`` and
    ``mismatches`` are ceilings, and ``"<op>_wire_ratio"`` budgets are
    ceilings on the merged wire/logical compression ratio — a quantized
    group drifting back toward 1.0 means compression silently stopped
    paying for itself.  Unknown groups in the baseline are ignored (a
    gate for a group that never ran is not a drift).  Flags and drift
    all count as issues.

    The special baseline key ``"__manifest__"`` (a path to raylint's
    ``comms_manifest.json`` or the inlined manifest dict) additionally
    cross-checks every ledgered group/op against the static collective
    plan via :func:`_manifest_drift`: ops the static analysis never
    planned report as ``<op>_unplanned`` drift."""
    from ray_tpu.observability import comms as comms_mod
    cluster = collected.get("cluster") or {}
    snaps = (cluster.get("metrics") or {}).get("snapshots") or {}
    payloads = []
    for families in snaps.values():
        p = comms_mod.extract_comms(families or [])
        if p:
            payloads.append(p)
    merged = comms_mod.merge_payloads(payloads)
    groups, bounds = merged["groups"], merged["bounds"]
    skew = comms_mod.skew_flags(groups, factor=factor, bounds=bounds)
    links = comms_mod.link_flags(merged["links"], factor=factor)
    report = comms_mod.skew_report(groups, bounds=bounds)
    drift = []
    base = dict(baseline or {})
    manifest = base.pop("__manifest__", None)
    if isinstance(manifest, str):
        try:
            with open(manifest, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            # a configured gate that cannot be read must fail loudly,
            # not silently pass
            drift.append({"group": "__manifest__",
                          "metric": "manifest_unreadable",
                          "got": 1, "baseline": 0.0, "tolerance": 1.0,
                          "error": str(e)})
            manifest = None
    if isinstance(manifest, dict):
        drift.extend(_manifest_drift(
            groups, manifest,
            tolerance=float(manifest.get("tolerance", 1.0))))
    for group, budgets in base.items():
        rec = groups.get(group)
        if rec is None:
            continue
        tolerance = float(budgets.get("tolerance", 1.0))
        for key, base in budgets.items():
            if key == "tolerance":
                continue
            if key.endswith("_gbps"):
                op = key[:-5]
                got = float(((rec.get("ops") or {}).get(op) or {})
                            .get("algbw_gbps", 0.0))
                if got < float(base) * tolerance:
                    drift.append({"group": group, "metric": key,
                                  "got_gbps": round(got, 3),
                                  "baseline_gbps": float(base),
                                  "tolerance": tolerance})
            elif key.endswith("_wire_ratio"):
                op = key[:-len("_wire_ratio")]
                o = ((rec.get("ops") or {}).get(op) or {})
                nbytes = float(o.get("bytes") or 0.0)
                wire = float(o.get("wire_bytes", nbytes) or nbytes)
                got = (wire / nbytes) if nbytes else 1.0
                if got > float(base) * tolerance:
                    drift.append({"group": group, "metric": key,
                                  "got_ratio": round(got, 4),
                                  "baseline_ratio": float(base),
                                  "tolerance": tolerance})
            elif key == "skew_p95_ms":
                ranks = report.get(group) or {}
                got = max((s["p95_ms"] for s in ranks.values()),
                          default=0.0)
                if got > float(base) * tolerance:
                    drift.append({"group": group, "metric": key,
                                  "got_ms": round(got, 3),
                                  "baseline_ms": float(base),
                                  "tolerance": tolerance})
            elif key == "mismatches":
                got = int(rec.get("mismatches") or 0)
                if got > float(base) * tolerance:
                    drift.append({"group": group, "metric": key,
                                  "got": got, "baseline": float(base),
                                  "tolerance": tolerance})
    return {"groups": groups, "links": merged["links"], "skew": report,
            "skew_flags": skew, "link_flags": links, "drift": drift}


def diagnose(collected: dict, straggler_factor: float = 3.0,
             perf_baseline: Optional[dict] = None,
             goodput_baseline: Optional[dict] = None,
             comms_baseline: Optional[dict] = None) -> dict:
    """Turn a :func:`collect` result into findings. Machine-readable;
    :func:`render_text` prints the same structure for humans."""
    crashes = _crash_reports(_all_bundles(collected))
    all_hangs = _hang_reports(collected)
    hangs = [h for h in all_hangs if not h.get("expected")]
    expected_hangs = [h for h in all_hangs if h.get("expected")]
    stragglers = _straggler_reports(collected, factor=straggler_factor)
    cluster = collected.get("cluster") or {}
    states = _node_states(collected)
    draining_ids = {nid for nid, st in states.items() if st == "DRAINING"}
    missing: List[dict] = []
    for key in ("forensics", "timeline"):
        for h in ((cluster.get(key) or {}).get("missing_hosts") or []):
            if all(m["node_id"] != h["node_id"] for m in missing):
                missing.append(h)
    for h in ((cluster.get("metrics") or {}).get("missing_hosts") or []):
        if all(m["node_id"] != h["node_id"] for m in missing):
            missing.append(h)
    # A DRAINING node that already quiesced its RPC server is expectedly
    # unreachable — mid-decommission, not an outage.
    missing = [m for m in missing
               if m.get("node_id", "") not in draining_ids]
    all_dead = [n for n in ((cluster.get("nodes") or {}).get("nodes")
                            or []) if not n.get("alive")]
    # "drained: <reason>" is the orchestrator's clean-decommission stamp —
    # the workloads were migrated, so the departure is not an issue.
    dead_nodes = [n for n in all_dead
                  if not (n.get("death_reason") or "").startswith("drained")]
    drained_nodes = [n for n in all_dead
                     if (n.get("death_reason") or "").startswith("drained")]
    progress = cluster.get("drain") or {}
    draining = []
    for n in ((cluster.get("nodes") or {}).get("nodes") or []):
        if n.get("state") != "DRAINING":
            continue
        nid = n.get("node_id", "")
        draining.append({"node_id": nid,
                         "drain_reason": n.get("drain_reason", ""),
                         "progress": progress.get(nid),
                         "heartbeat_misses": [
                             h["consecutive_misses"]
                             for h in expected_hangs
                             if nid.startswith(h["node"])
                             or h["node"].startswith(nid[:8])]})
    # A daemon whose preemption probe keeps failing is flying blind: the
    # real eviction notice may never be seen, so the node would die with
    # no drain at all.
    from ray_tpu._private.config import _config
    preempt = cluster.get("preempt") or {}
    probe_threshold = _config.get("preempt_probe_failure_threshold")
    probe_flags = [
        {"node_id": nid, "consecutive_failures": n}
        for nid, n in sorted((preempt.get("probe_failures") or {}).items())
        if n >= probe_threshold]
    local = collected.get("local") or {}
    perf_section = _perf_reports(collected, baseline=perf_baseline)
    goodput_section = _goodput_reports(collected,
                                       baseline=goodput_baseline)
    comms_section = _comms_reports(collected, baseline=comms_baseline,
                                   factor=straggler_factor)
    # A flapping knob means the autopilot and the telemetry disagree
    # every few ticks — the controller froze it, and the operator should
    # know which policy is oscillating.
    autopilot_raw = cluster.get("autopilot") or {}
    decisions = autopilot_raw.get("decisions") or []
    flap_flags = [{"knob": k, "actuations": n}
                  for k, n in sorted(
                      (autopilot_raw.get("flapping") or {}).items())]
    reverts = [d for d in decisions if d.get("action") == "reverted"]
    autopilot_section = {
        "decisions": decisions,
        "reverts": reverts,
        "flap_flags": flap_flags,
        "flap_window_s": autopilot_raw.get("flap_window_s"),
    }
    n_issues = (len(crashes) + len(hangs) + len(stragglers) +
                len(missing) + len(dead_nodes) + len(probe_flags) +
                len(flap_flags) +
                len(perf_section["drift"]) +
                len(goodput_section["drift"]) +
                len(comms_section["skew_flags"]) +
                len(comms_section["link_flags"]) +
                len(comms_section["drift"]))
    return {
        "ts": collected.get("ts"),
        "healthy": n_issues == 0,
        "num_issues": n_issues,
        "perf": perf_section,
        "goodput": goodput_section,
        "comms": comms_section,
        "autopilot": autopilot_section,
        "crashes": crashes,
        "hangs": hangs,
        "stragglers": stragglers,
        "unreachable_hosts": missing,
        "preempt": preempt,
        "probe_flags": probe_flags,
        "draining_nodes": draining,
        "drained_nodes": [{"node_id": n.get("node_id", ""),
                           "death_reason": n.get("death_reason", "")}
                          for n in drained_nodes],
        "dead_nodes": [{"node_id": n.get("node_id", ""),
                        "death_reason": n.get("death_reason", "")}
                       for n in dead_nodes],
        "sealed_now": collected.get("sealed_now") or [],
        "flight_dir": local.get("root", ""),
        "recordings": len(local.get("recordings") or []),
        "bundles": len(local.get("bundles") or []),
        "collection_errors": collected.get("errors") or [],
    }


def render_text(report: dict) -> str:
    """Human-readable diagnosis of a :func:`diagnose` report."""
    lines = []
    lines.append("ray_tpu doctor")
    lines.append(f"  flight dir: {report.get('flight_dir') or '(default)'}"
                 f"  recordings: {report.get('recordings', 0)}"
                 f"  sealed bundles: {report.get('bundles', 0)}")
    if report.get("sealed_now"):
        lines.append(f"  sealed {len(report['sealed_now'])} orphaned "
                     "recording(s) this run:")
        for p in report["sealed_now"]:
            lines.append(f"    {p}")
    crashes = report.get("crashes") or []
    if crashes:
        lines.append("")
        lines.append(f"CRASHES ({len(crashes)})")
        for c in crashes:
            who = c["label"] or c["role"]
            lines.append(f"  [{who} pid={c['pid']}] {c['exit_reason']}")
            lines.append(f"    sealed by: {c['sealed_by']}")
            if c.get("exception"):
                lines.append(f"    exception: {c['exception']}")
            for t in c["inflight_tasks"]:
                lines.append(
                    f"    in-flight: {t['name']} "
                    f"(task {t['task_id'][:8]}"
                    + (f", trace {t['trace_id']}" if t["trace_id"]
                       else "") + ")")
            if c["trace_ids"]:
                lines.append("    trace ids: " + ", ".join(c["trace_ids"]))
            if c["chaos_spec"]:
                lines.append(f"    chaos spec: {c['chaos_spec']}")
            for cl in c["chaos_points_fired"][-3:]:
                lines.append(f"    chaos fired: {cl}")
            if c.get("heartbeat_misses"):
                lines.append("    control plane already degraded: "
                             f"{c['heartbeat_misses']} consecutive "
                             "heartbeat misses at death")
            for log_line in c["last_logs"][-3:]:
                lines.append(f"    log: {log_line}")
    hangs = report.get("hangs") or []
    if hangs:
        lines.append("")
        lines.append(f"HANGS ({len(hangs)})")
        for h in hangs:
            lines.append(f"  node {h['node']}: "
                         f"{h['consecutive_misses']:.0f} consecutive "
                         "heartbeat misses")
            for name in h["inflight_tasks"]:
                lines.append(f"    in-flight: {name}")
            for tname in sorted(h.get("stacks") or {}):
                lines.append(f"    stack sampled: thread {tname}")
    probe_flags = report.get("probe_flags") or []
    if probe_flags:
        lines.append("")
        lines.append(f"BLIND PREEMPTION WATCHERS ({len(probe_flags)})")
        for p in probe_flags:
            lines.append(
                f"  node {p['node_id'][:8]}: "
                f"{p['consecutive_failures']} consecutive preempt-probe "
                "failures — an eviction notice may never be seen")
    draining = report.get("draining_nodes") or []
    if draining:
        lines.append("")
        lines.append(f"DRAINING ({len(draining)}) — migration in "
                     "progress, not an issue")
        for d in draining:
            lines.append(f"  node {d['node_id'][:8]}: "
                         f"{d.get('drain_reason') or '(no reason)'}")
            prog = d.get("progress") or {}
            if prog:
                lines.append(
                    f"    phase: {prog.get('phase', '?')}  "
                    f"tasks pending: {prog.get('tasks_pending', '?')}  "
                    f"actors checkpointed: "
                    f"{prog.get('actors_checkpointed', '?')}  "
                    f"objects migrated: "
                    f"{prog.get('objects_migrated', '?')}")
            for misses in d.get("heartbeat_misses") or []:
                lines.append(f"    {misses:.0f} heartbeat miss(es): "
                             "draining (expected)")
    drained = report.get("drained_nodes") or []
    if drained:
        lines.append("")
        lines.append(f"DRAINED NODES ({len(drained)}) — clean "
                     "decommission, workloads migrated")
        for n in drained:
            lines.append(f"  {n['node_id'][:8]}: {n['death_reason']}")
    stragglers = report.get("stragglers") or []
    if stragglers:
        lines.append("")
        lines.append(f"STRAGGLERS ({len(stragglers)})")
        for s in stragglers:
            lines.append(
                f"  {s['process']}: task {s['task']} mean "
                f"{s['mean_us']}us = {s['slowdown']}x the cluster "
                f"median ({s['cluster_median_us']}us, "
                f"{s['samples']} samples)")
    perf_section = report.get("perf") or {}
    quantiles = perf_section.get("cluster") or {}
    if quantiles:
        lines.append("")
        lines.append(f"PERF ({len(quantiles)} histogram(s), "
                     "cluster-merged)")
        for name, s in quantiles.items():
            lines.append(
                f"  {name}: n={s['count']:.0f} p50={s['p50_ms']:.2f}ms "
                f"p95={s['p95_ms']:.2f}ms p99={s['p99_ms']:.2f}ms")
    drift = perf_section.get("drift") or []
    if drift:
        lines.append("")
        lines.append(f"PERF DRIFT ({len(drift)}) — quantiles beyond "
                     "recorded baseline")
        for d in drift:
            lines.append(
                f"  {d['hist']}.{d['metric']}: {d['got_ms']}ms > "
                f"{d['baseline_ms']}ms x{d['tolerance']}")
    goodput_section = report.get("goodput") or {}
    gjobs = goodput_section.get("jobs") or {}
    if gjobs:
        lines.append("")
        lines.append(f"GOODPUT ({len(gjobs)} job(s), cluster-merged)")
        for job, rec in sorted(gjobs.items()):
            cats = rec.get("cats") or {}
            busy = ", ".join(
                f"{c}={cats[c]:.1f}s" for c in sorted(cats)
                if cats.get(c, 0.0) > 0.0)
            lines.append(
                f"  {job}: goodput {rec.get('goodput_pct', 0.0):.1f}% of "
                f"{rec.get('wall_s', 0.0):.1f} node-seconds "
                f"(compiles={rec.get('compile_count', 0)}, "
                f"recompiles={rec.get('recompile_count', 0)})")
            if busy:
                lines.append(f"    {busy}")
    gdrift = goodput_section.get("drift") or []
    if gdrift:
        lines.append("")
        lines.append(f"GOODPUT DRIFT ({len(gdrift)}) — efficiency "
                     "beyond recorded budget")
        for d in gdrift:
            if "got_pct" in d:
                lines.append(
                    f"  {d['job']}.{d['metric']}: {d['got_pct']}% < "
                    f"{d['baseline_pct']}% x{d['tolerance']}")
            else:
                lines.append(
                    f"  {d['job']}.{d['metric']}: {d['got_s']}s > "
                    f"{d['baseline_s']}s x{d['tolerance']}")
    comms_section = report.get("comms") or {}
    cgroups = comms_section.get("groups") or {}
    if cgroups:
        lines.append("")
        lines.append(f"COMMS ({len(cgroups)} group(s), cluster-merged)")
        for gname, rec in sorted(cgroups.items()):
            for op, o in sorted((rec.get("ops") or {}).items()):
                lines.append(
                    f"  {gname}.{op}: n={o.get('count', 0)} "
                    f"{o.get('bytes', 0) / 1e6:.1f}MB "
                    f"algbw={o.get('algbw_gbps', 0.0):.2f}GB/s "
                    f"busbw={o.get('busbw_gbps', 0.0):.2f}GB/s")
            if rec.get("mismatches"):
                lines.append(f"  {gname}: {rec['mismatches']} collective "
                             "fingerprint mismatch(es) — divergent ranks")
        for fl in comms_section.get("skew_flags") or []:
            lines.append(
                f"  LAGGARD {fl['group']} rank {fl['rank']}: arrival-skew "
                f"p95 {fl['p95_ms']:.1f}ms vs peer median "
                f"{fl['median_ms']:.1f}ms ({fl['samples']} samples)")
        for fl in comms_section.get("link_flags") or []:
            lines.append(
                f"  LINK {fl['peer']} ({fl['consumer']}): {fl['why']}")
    cdrift = comms_section.get("drift") or []
    if cdrift:
        lines.append("")
        lines.append(f"COMMS DRIFT ({len(cdrift)}) — bandwidth/skew/plan "
                     "beyond recorded budget")
        for d in cdrift:
            if "got_gbps" in d:
                lines.append(
                    f"  {d['group']}.{d['metric']}: {d['got_gbps']}GB/s < "
                    f"{d['baseline_gbps']}GB/s x{d['tolerance']}")
            elif "got_ms" in d:
                lines.append(
                    f"  {d['group']}.{d['metric']}: {d['got_ms']}ms > "
                    f"{d['baseline_ms']}ms x{d['tolerance']}")
            elif "got_ratio" in d:
                lines.append(
                    f"  {d['group']}.{d['metric']}: {d['got_ratio']} > "
                    f"{d['baseline_ratio']} x{d['tolerance']}")
            elif d["metric"].endswith("_unplanned"):
                lines.append(
                    f"  {d['group']}.{d['metric']}: {d['got']} op(s) "
                    "ledgered but absent from comms_manifest.json — "
                    "unplanned collective")
            else:
                lines.append(
                    f"  {d['group']}.{d['metric']}: {d['got']} > "
                    f"{d['baseline']} x{d['tolerance']}")
    ap = report.get("autopilot") or {}
    decisions = ap.get("decisions") or []
    if decisions or ap.get("flap_flags"):
        lines.append("")
        lines.append(f"AUTOPILOT ({len(decisions)} journaled "
                     "decision(s))")
        for d in decisions[-10:]:
            lines.append(
                f"  {d.get('action', '?'):8s} "
                f"{d.get('knob', '?')}: {d.get('old')} -> {d.get('new')}"
                + (f"  ({d.get('reason')})" if d.get("reason") else ""))
        reverts = ap.get("reverts") or []
        if reverts:
            lines.append(f"  {len(reverts)} change(s) auto-reverted on "
                         "SLO regression (see --explain <knob>)")
        for fl in ap.get("flap_flags") or []:
            lines.append(
                f"  FLAPPING {fl['knob']}: {fl['actuations']} actuations "
                f"inside {ap.get('flap_window_s', 0):.0f}s — frozen by "
                "the controller; policy and telemetry disagree")
    missing = report.get("unreachable_hosts") or []
    if missing:
        lines.append("")
        lines.append(f"UNREACHABLE HOSTS ({len(missing)})")
        for m in missing:
            lines.append(f"  {m['node_id'][:8]} @ {m['address']}: "
                         f"{m['error']}")
    dead = report.get("dead_nodes") or []
    if dead:
        lines.append("")
        lines.append(f"DEAD NODES ({len(dead)})")
        for n in dead:
            lines.append(f"  {n['node_id'][:8]}: "
                         f"{n['death_reason'] or '(no reason recorded)'}")
    errs = report.get("collection_errors") or []
    if errs:
        lines.append("")
        lines.append(f"COLLECTION ERRORS ({len(errs)})")
        for e in errs:
            lines.append(f"  {e}")
    lines.append("")
    if report.get("healthy"):
        lines.append("verdict: healthy — no crashes, hangs, stragglers "
                     "or unreachable hosts")
    else:
        lines.append(f"verdict: {report.get('num_issues')} issue(s) found")
    return "\n".join(lines) + "\n"


def explain_knob(report: dict, knob: str) -> dict:
    """Why does ``knob`` have its value?  Replays the autopilot journal
    for one knob: every decision with its evidence snapshot, the
    guardrail bounds in force, which changes were clamped or reverted,
    and whether the knob is currently flap-frozen."""
    ap = report.get("autopilot") or {}
    decisions = [d for d in (ap.get("decisions") or [])
                 if d.get("knob") == knob]
    flapping = next((fl for fl in (ap.get("flap_flags") or [])
                     if fl.get("knob") == knob), None)
    return {
        "knob": knob,
        "decisions": decisions,
        "reverts": [d for d in decisions
                    if d.get("action") == "reverted"],
        "current": decisions[-1].get("new") if decisions else None,
        "flapping": flapping,
        "flap_window_s": ap.get("flap_window_s"),
    }


def render_explain(explain: dict) -> str:
    """Human-readable decision history for one knob."""
    knob = explain.get("knob", "?")
    decisions = explain.get("decisions") or []
    lines = [f"ray_tpu doctor --explain {knob}"]
    if not decisions:
        lines.append("  no journaled decisions — the autopilot never "
                     "touched this knob (or the journal expired)")
        return "\n".join(lines) + "\n"
    lines.append(f"  current value: {explain.get('current')}  "
                 f"({len(decisions)} decision(s), "
                 f"{len(explain.get('reverts') or [])} revert(s))")
    if explain.get("flapping"):
        fl = explain["flapping"]
        lines.append(
            f"  FLAPPING: {fl['actuations']} actuations inside "
            f"{explain.get('flap_window_s', 0):.0f}s — frozen by the "
            "controller; the policy and the telemetry disagree")
    for d in decisions:
        ts = d.get("ts")
        stamp = (time.strftime("%H:%M:%S", time.localtime(float(ts)))
                 if ts else "?")
        lines.append(f"  [{stamp}] {d.get('action', '?')}: "
                     f"{d.get('old')} -> {d.get('new')}")
        if d.get("reason"):
            lines.append(f"    why: {d['reason']}")
        if d.get("bounds"):
            lines.append(f"    guardrail bounds: {d['bounds']}")
        ev = d.get("evidence") or {}
        if ev:
            body = ", ".join(f"{k}={ev[k]}" for k in sorted(ev))
            lines.append(f"    evidence: {body}")
        if d.get("ttl_s"):
            lines.append(f"    claim TTL: {float(d['ttl_s']):.0f}s")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.doctor",
        description="Collect crash bundles + live cluster state and "
                    "diagnose crashes, hangs and stragglers.")
    parser.add_argument("--flight-dir", default=None,
                        help="flight recorder root (default: the "
                             "flight_recorder_dir config knob)")
    parser.add_argument("--address", default=None,
                        help="state service host:port for live "
                             "cluster-wide collection (omit for "
                             "disk-only post-mortem)")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable report")
    parser.add_argument("--no-seal", action="store_true",
                        help="do not posthumously seal orphaned "
                             "recordings, only read")
    parser.add_argument("--out", default=None,
                        help="also write the JSON report to this file "
                             "(atomic)")
    parser.add_argument("--straggler-factor", type=float, default=3.0,
                        help="flag a process whose mean task time "
                             "exceeds this multiple of the cluster "
                             "median (default 3.0)")
    parser.add_argument("--perf-baseline", default=None,
                        help="JSON file of per-histogram quantile "
                             "budgets ({name: {p99_ms: X, tolerance: "
                             "1.5}}); quantiles beyond budget*tolerance "
                             "count as issues")
    parser.add_argument("--goodput-baseline", default=None,
                        help="JSON file of per-job goodput budgets "
                             "({job: {goodput_pct: floor, "
                             "restart_downtime_s: ceiling, tolerance: "
                             "1.0}}); budget violations count as issues")
    parser.add_argument("--explain", default=None, metavar="KNOB",
                        help="render the autopilot's decision journal "
                             "for one knob: evidence, guardrail bounds, "
                             "reverts and flap state (with --json the "
                             "explanation is embedded under 'explain')")
    parser.add_argument("--comms-baseline", default=None,
                        help="JSON file of per-group comms budgets "
                             "({group: {allreduce_gbps: floor, "
                             "skew_p95_ms: ceiling, mismatches: ceiling, "
                             "tolerance: 1.0}}); the special key "
                             "'__manifest__' (path to raylint's "
                             "comms_manifest.json, or the inlined "
                             "manifest) cross-checks the ledger against "
                             "the static collective plan — ledgered ops "
                             "absent from the plan report as unplanned "
                             "drift; budget violations count as issues")
    args = parser.parse_args(argv)
    perf_baseline = None
    if args.perf_baseline:
        with open(args.perf_baseline) as f:
            perf_baseline = json.load(f)
    goodput_baseline = None
    if args.goodput_baseline:
        with open(args.goodput_baseline) as f:
            goodput_baseline = json.load(f)
    comms_baseline = None
    if args.comms_baseline:
        with open(args.comms_baseline) as f:
            comms_baseline = json.load(f)
    try:
        collected = collect(flight_dir=args.flight_dir,
                            address=args.address,
                            seal=not args.no_seal)
        report = diagnose(collected,
                          straggler_factor=args.straggler_factor,
                          perf_baseline=perf_baseline,
                          goodput_baseline=goodput_baseline,
                          comms_baseline=comms_baseline)
    except Exception as e:  # noqa: BLE001
        print(f"doctor: collection failed: {e!r}", file=sys.stderr)
        return 2
    explain = None
    if args.explain:
        explain = explain_knob(report, args.explain)
        report["explain"] = explain
    if args.out:
        from ray_tpu.checkpoint.manifest import atomic_write_bytes
        atomic_write_bytes(args.out,
                           json.dumps(report, indent=2).encode())
    if args.json:
        print(json.dumps(report, indent=2))
    elif explain is not None:
        print(render_explain(explain), end="")
    else:
        print(render_text(report), end="")
    return 0
