"""The guardrailed actuator layer — the only path that moves a knob.

An :class:`Actuator` binds one autopilot-owned knob to a getter/setter
pair plus the guardrail bounds from :mod:`ray_tpu.autopilot.knobs`.
:func:`apply` is the single write path: it clamps the proposal to
bounds, fires the ``autopilot.apply`` chaos point, performs the write,
and journals the decision (evidence snapshot, old -> new, bounds, TTL)
— on *any* actuation fault the previous value is restored before the
error propagates, so a half-applied decision can never survive.  The
raylint R26 rule enforces that runtime code outside this package never
writes an owned config knob directly.

Two actuator families exist:

- **config actuators** (:func:`config_actuator`) write through the
  process-wide ``_config`` registry.  Their consumers already re-read
  the knob on every use (``transport.streams_per_peer()``, the
  collective ``_resolve_config``, ``Dataset.iter_batches``'s prefetch
  default, the cadence controller's override consult), which is what
  makes a registry write *live* tuning rather than a restart request.
- **callback actuators** registered by subsystems that own non-registry
  state — the serve controller registers ``serve.<deployment>.*``
  actuators that push retuned batch config to live replicas.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import chaos
from ray_tpu._private.config import _config
from ray_tpu.autopilot import knobs as _knobs
from ray_tpu.autopilot.journal import (APPLIED, CLAMPED, FAILED, REJECTED,
                                       Decision, Journal)

logger = logging.getLogger("ray_tpu")


@dataclass
class Actuator:
    """One tunable knob: accessors + the guardrails :func:`apply`
    enforces.  ``lo``/``hi`` clamp numeric values; ``choices`` validates
    enum values; exactly one family applies per actuator."""

    name: str
    get: Callable[[], Any]
    set: Callable[[Any], None]
    kind: str = "int"  # "int" | "float" | "enum"
    lo: Optional[float] = None
    hi: Optional[float] = None
    choices: Optional[Tuple[Any, ...]] = None

    def bounds(self) -> List[Any]:
        if self.kind == "enum":
            return list(self.choices or ())
        return [self.lo, self.hi]


class ActuatorRegistry:
    """Named actuators; thread-safe (subsystems register from their own
    control threads, the autopilot reads from its tick thread)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._actuators: Dict[str, Actuator] = {}  # raylint: guarded-by(self._lock)

    def register(self, actuator: Actuator) -> None:
        with self._lock:
            self._actuators[actuator.name] = actuator

    def unregister(self, name: str) -> None:
        with self._lock:
            self._actuators.pop(name, None)

    def get(self, name: str) -> Optional[Actuator]:
        with self._lock:
            return self._actuators.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._actuators)


#: the process-global registry the dashboard-hosted controller drives;
#: the A/B drill builds private registries instead
_REGISTRY = ActuatorRegistry()


def registry() -> ActuatorRegistry:
    return _REGISTRY


def apply(name: str, value: Any, evidence: Dict[str, Any], *,
          journal: Journal, reg: Optional[ActuatorRegistry] = None,
          ttl_s: Optional[float] = None, reason: str = "",
          action: str = APPLIED) -> Optional[Decision]:
    """THE guardrailed write path (see module docstring).

    Returns the journaled :class:`Decision`, or ``None`` when the
    clamped proposal equals the current value (no-ops are not
    journaled — a journal of non-changes would bury the real story).
    Raises on unknown actuator, invalid enum value, or actuation fault
    — after journaling, and after restoring the previous value.
    """
    reg = reg or _REGISTRY
    if ttl_s is None:
        ttl_s = float(_config.get("autopilot_decision_ttl_s"))
    act = reg.get(name)
    if act is None:
        journal.record(Decision(knob=name, old=None, new=value,
                                action=REJECTED, evidence=dict(evidence),
                                reason="unknown actuator"))
        raise KeyError(f"autopilot: no actuator registered for {name!r}")

    # guardrail: clamp numeric proposals, validate enum proposals
    clamped = value
    if act.kind == "enum":
        if act.choices and value not in act.choices:
            journal.record(Decision(
                knob=name, old=act.get(), new=value, action=REJECTED,
                evidence=dict(evidence), bounds=act.bounds(),
                reason=f"not in {act.choices}"))
            raise ValueError(
                f"autopilot: {name}={value!r} not in {act.choices}")
    else:
        caster = int if act.kind == "int" else float
        clamped = caster(value)
        if act.lo is not None and clamped < act.lo:
            clamped = caster(act.lo)
        if act.hi is not None and clamped > act.hi:
            clamped = caster(act.hi)
        if clamped != value and action == APPLIED:
            action = CLAMPED

    old = act.get()
    if clamped == old:
        return None

    try:
        if chaos.ENABLED:
            # the chaos point guards the write: an injected fault here
            # (tests: "autopilot.apply=error") must leave `old` intact
            chaos.inject("autopilot.apply", knob=name)
        act.set(clamped)
    except Exception as e:  # noqa: BLE001 — journal + restore, then raise
        try:
            act.set(old)
        except Exception as restore_err:  # noqa: BLE001
            logger.warning("autopilot: restore of %s failed: %s", name,
                           restore_err)
        journal.record(Decision(
            knob=name, old=old, new=clamped, action=FAILED,
            evidence=dict(evidence), bounds=act.bounds(), ttl_s=ttl_s,
            reason=repr(e)))
        raise
    return journal.record(Decision(
        knob=name, old=old, new=clamped, action=action,
        evidence=dict(evidence), bounds=act.bounds(), ttl_s=ttl_s,
        reason=reason))


def config_actuator(knob: str,
                    store: Optional[Dict[str, Any]] = None) -> Actuator:
    """Actuator for one :data:`~ray_tpu.autopilot.knobs.OWNED_KNOBS`
    entry.  Default backing is the process ``_config`` registry (this
    module is the R26-allowlisted write path); pass ``store`` to back it
    with a plain dict instead (the A/B drill's isolated knob store)."""
    spec = _knobs.OWNED_KNOBS[knob]
    if store is None:
        def _get(k=knob):
            return _config.get(k)

        def _set(v, k=knob):
            _config.set(k, v)
    else:
        def _get(k=knob, s=store):
            return s[k]

        def _set(v, k=knob, s=store):
            s[k] = v
    return Actuator(name=knob, get=_get, set=_set,
                    kind=str(spec.get("kind", "int")),
                    lo=spec.get("lo"), hi=spec.get("hi"),
                    choices=tuple(spec["choices"])
                    if "choices" in spec else None)


def register_config_actuators(
        reg: Optional[ActuatorRegistry] = None,
        store: Optional[Dict[str, Any]] = None) -> List[str]:
    """Register an actuator for every owned config knob; returns the
    names.  Idempotent — re-registration replaces."""
    reg = reg or _REGISTRY
    names = []
    for knob in sorted(_knobs.OWNED_KNOBS):
        reg.register(config_actuator(knob, store=store))
        names.append(knob)
    return names
