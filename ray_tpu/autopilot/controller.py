"""The autopilot controller loop.

One :class:`Autopilot` per cluster, hosted by the dashboard head (the
process that already federates ``/api/perf`` + ``/api/goodput`` +
``/api/comms``): every tick it snapshots the three planes, runs the
policy catalog, routes surviving proposals through the guardrailed
actuator layer, and then *watches what it did* — each actuation arms an
SLO watch that compares the guarded metric against its pre-change
baseline for ``autopilot_watch_ticks`` ticks and rolls the knob back
(journaled, ``action="reverted"``) the moment it regresses beyond
``autopilot_revert_pct``.  Tick-driven with an event hook
(:meth:`poke`) like the autoscaler, so a plane can wake it early.

Safety ladder, outermost first:

1. policies are pure — a bad rule can only *propose*;
2. ``actuators.apply`` clamps to the registered bounds and restores the
   previous value on any actuation fault;
3. at most ``autopilot_max_changes_per_tick`` actuations per tick;
4. the post-change SLO watch auto-reverts regressions;
5. a knob actuated >= 3 times inside ``autopilot_flap_window_s`` is
   frozen for the remainder of the window (the doctor flags it too);
6. every one of the above leaves a journal record the doctor's
   ``--explain <knob>`` can replay.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.config import _config
from ray_tpu.autopilot import actuators as _actuators
from ray_tpu.autopilot import policies as _policies
from ray_tpu.autopilot.journal import REVERTED, Journal

logger = logging.getLogger("ray_tpu")

#: knobs actuated at least this many times per flap window are frozen
FLAP_THRESHOLD = 3


def slo_value(snapshot: Dict[str, Any],
              slo: Dict[str, Any]) -> Optional[float]:
    """Evaluate one proposal's guarded metric on a snapshot.  Returns
    None when the metric is absent (watch keeps waiting — absence of
    telemetry is not evidence of regression)."""
    kind = slo.get("kind")
    if kind == "goodput_pct":
        jobs = (snapshot.get("goodput") or {}).get("jobs") or {}
        if slo.get("job") in jobs:
            return float(jobs[slo["job"]].get("goodput_pct") or 0.0)
        if not jobs:
            return None
        wall = sum(float(r.get("wall_s") or 0.0) for r in jobs.values())
        compute = sum(float((r.get("cats") or {}).get("compute") or 0.0)
                      for r in jobs.values())
        return 100.0 * compute / wall if wall > 0 else None
    if kind == "perf_p95":
        hist = ((snapshot.get("perf") or {}).get("cluster") or {}).get(
            slo.get("hist")) or {}
        if not hist.get("count"):
            return None
        return float(hist.get("p95_ms") or 0.0)
    return None


def slo_higher_is_better(slo: Dict[str, Any]) -> bool:
    return slo.get("kind") != "perf_p95"


class Autopilot:
    """See module docstring.  ``snapshot_fn`` returns the plane merge
    (``{"perf": ..., "goodput": ..., "comms": ...}`` — the dashboard
    head passes its own ``_perf/_goodput/_comms``); ``hazard_fn``
    optionally feeds the fleet hazard rate for the cadence policy."""

    def __init__(self, snapshot_fn: Callable[[], Dict[str, Any]],
                 journal: Optional[Journal] = None,
                 reg: Optional[_actuators.ActuatorRegistry] = None,
                 hazard_fn: Optional[Callable[[], Optional[float]]] = None,
                 clock=time.time):
        self._snapshot_fn = snapshot_fn
        self.journal = journal or Journal(clock=clock)
        self.registry = reg or _actuators.registry()
        self._hazard_fn = hazard_fn
        self._clock = clock
        #: tick-thread state that status() reads from the dashboard's
        #: HTTP thread — everything below shares one guard
        self._lock = threading.Lock()
        # raylint: guarded-by(self._lock)
        self._watches: List[Dict[str, Any]] = []
        # raylint: guarded-by(self._lock)
        self.ticks = 0
        # raylint: guarded-by(self._lock)
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- knob access -----------------------------------------------------

    def _get(self, knob: str) -> Any:
        act = self.registry.get(knob)
        if act is not None:
            return act.get()
        return _config.get(knob)

    # -- one tick --------------------------------------------------------

    def tick(self, snapshot: Optional[Dict[str, Any]] = None) -> List[Any]:
        """One control cycle; returns the decisions journaled this tick
        (reverts first, then fresh actuations)."""
        with self._lock:
            self.ticks += 1
        if snapshot is None:
            snapshot = self._snapshot_fn()
        if self._hazard_fn is not None and \
                "hazard_rate_per_hour" not in snapshot:
            try:
                rate = self._hazard_fn()
                if rate is not None:
                    snapshot["hazard_rate_per_hour"] = rate
            except Exception as e:  # noqa: BLE001
                logger.debug("autopilot: hazard feed failed: %s", e)
        decisions: List[Any] = []
        decisions += self._check_watches(snapshot)
        frozen = self.journal.flapping(
            float(_config.get("autopilot_flap_window_s")),
            FLAP_THRESHOLD, now=self._clock())
        budget = int(_config.get("autopilot_max_changes_per_tick"))
        with self._lock:
            watched = {w["knob"] for w in self._watches}
        for proposal in _policies.propose(snapshot, self._get,
                                          self.registry.names()):
            if budget <= 0:
                break
            knob = proposal["knob"]
            if knob in frozen:
                logger.info("autopilot: %s frozen (%d changes in flap "
                            "window)", knob, frozen[knob])
                continue
            if knob in watched:
                continue  # one in-flight experiment per knob at a time
            baseline = slo_value(snapshot, proposal["slo"])
            try:
                dec = _actuators.apply(
                    knob, proposal["value"], proposal["evidence"],
                    journal=self.journal, reg=self.registry,
                    reason=proposal.get("reason", ""))
            except Exception as e:  # noqa: BLE001 — journaled by apply
                with self._lock:
                    self.last_error = repr(e)
                continue
            if dec is None:
                continue
            budget -= 1
            decisions.append(dec)
            watched.add(knob)
            with self._lock:
                self._watches.append({
                    "knob": knob, "old": dec.old, "new": dec.new,
                    "slo": dict(proposal["slo"]), "baseline": baseline,
                    "ticks_left": int(_config.get("autopilot_watch_ticks")),
                    "expires": (float(dec.ts) + float(dec.ttl_s))
                    if dec.ttl_s else None,
                })
        return decisions

    def _check_watches(self, snapshot: Dict[str, Any]) -> List[Any]:
        """Evaluate armed SLO watches; revert regressions, retire
        watches whose window (or decision TTL) elapsed."""
        revert_pct = float(_config.get("autopilot_revert_pct"))
        now = self._clock()
        decisions: List[Any] = []
        kept: List[Dict[str, Any]] = []
        # the tick thread is the sole mutator; the lock orders the list
        # swap against concurrent status() readers
        with self._lock:
            pending = list(self._watches)
        for w in pending:
            cur = slo_value(snapshot, w["slo"])
            baseline = w.get("baseline")
            regressed = False
            if cur is not None and baseline is not None and baseline > 0:
                if slo_higher_is_better(w["slo"]):
                    regressed = cur < baseline * (1.0 - revert_pct / 100.0)
                else:
                    regressed = cur > baseline * (1.0 + revert_pct / 100.0)
            if regressed:
                try:
                    dec = _actuators.apply(
                        w["knob"], w["old"],
                        {"slo": w["slo"], "baseline": baseline,
                         "observed": cur, "revert_pct": revert_pct},
                        journal=self.journal, reg=self.registry,
                        action=REVERTED,
                        reason=f"SLO regressed: {cur:.3f} vs baseline "
                               f"{baseline:.3f}")
                    if dec is not None:
                        decisions.append(dec)
                except Exception as e:  # noqa: BLE001
                    with self._lock:
                        self.last_error = repr(e)
                continue  # watch retires either way: the change is gone
            w["ticks_left"] -= 1
            expired = w["expires"] is not None and now >= w["expires"]
            if w["ticks_left"] > 0 and not expired:
                kept.append(w)
            # a watch that survives its window is a kept change: the
            # journal's applied record stands, nothing new to write
        with self._lock:
            self._watches = kept
        return decisions

    # -- hosting ---------------------------------------------------------

    def poke(self) -> None:
        """Event hook: wake the tick thread before its period elapses
        (a plane merge just saw something worth reacting to)."""
        self._wake.set()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autopilot")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self.last_error = repr(e)
                logger.warning("autopilot tick failed: %s", e)
            self._wake.wait(float(_config.get("autopilot_tick_s")))
            self._wake.clear()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            ticks = self.ticks
            last_error = self.last_error
            watches = [{k: w[k] for k in
                        ("knob", "old", "new", "baseline", "ticks_left")}
                       for w in self._watches]
        return {
            "ticks": ticks,
            "actuators": self.registry.names(),
            "watches": watches,
            "flapping": self.journal.flapping(
                float(_config.get("autopilot_flap_window_s")),
                FLAP_THRESHOLD, now=self._clock()),
            "last_error": last_error,
            "journal": self.journal.tail(50),
        }
