"""Autopilot-owned knob registry — the single source of ownership truth.

Deliberately **import-free** (same contract as
``ray_tpu/observability/metric_names.py``): the raylint R26
actuator-bypass rule ``exec``\\ s this file's source inside the static
analyzer, so importing anything here would drag the runtime (config
singleton, sockets, JAX) into a lint process.

A knob listed in :data:`OWNED_KNOBS` is **owned by the autopilot**: once
the cluster controller is responsible for it, any runtime write outside
the guardrailed ``ray_tpu.autopilot.actuators.apply()`` path would fork
control of the knob between the operator and the controller — the
controller's journal would no longer explain the knob's value, and its
SLO watch/revert guarantee would silently not cover the foreign write.
R26 flags such writes; tests may pin owned knobs under the scoped allow
profile in ``run_static_analysis.sh``.

Each entry carries the guardrail bounds the actuator layer enforces:
``lo``/``hi`` clamp numeric proposals, ``choices`` validates enum
proposals.  Bounds live here — next to ownership — so the linter, the
actuators and the doctor all read one table.
"""

# knob name -> guardrail spec
#   kind: "int" | "float" | "enum"
#   lo/hi: inclusive clamp bounds (numeric kinds)
#   choices: valid values (enum kind)
OWNED_KNOBS = {
    # transport: lifelong successor to the one-shot startup probe
    "data_streams_per_peer": {"kind": "int", "lo": 1, "hi": 16},
    "fetch_chunk_bytes": {"kind": "int", "lo": 256 * 1024,
                          "hi": 64 * 1024 * 1024},
    # collective wire scheme + hierarchy (per-group busbw evidence)
    "collective_compression": {"kind": "enum",
                               "choices": ("none", "q8", "fp8")},
    "collective_ranks_per_host": {"kind": "int", "lo": 0, "hi": 64},
    # data plane: prefetch depth from data_wait attribution
    "data_prefetch_batches": {"kind": "int", "lo": 0, "hi": 8},
    # checkpoint cadence override (the migrated PR 17 hazard loop)
    "checkpoint_cadence_autopilot_steps": {"kind": "int", "lo": 0,
                                           "hi": 100_000},
}
