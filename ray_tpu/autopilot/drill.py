"""The autopilot A/B acceptance drill.

A deterministic, virtual-time miniature of the cluster: a 24-step train
loop degraded by a *fixed seeded chaos schedule* — a delayed data
reader, one slow collective rank — plus a misconfigured serve linger
window, rendered through the real telemetry merge math
(``goodput.merge_payloads``, ``comms.merge_payloads``, the perf
histogram shapes) into the exact snapshot the controller's policies
consume.  The drill then runs the *same* workload twice: once with the
autopilot ticking (private actuator registry + in-memory journal, never
the process ``_config``) and once without, and compares the merged
``goodput_pct``.  The autopilot arm must win strictly — that delta is
the ``autopilot_goodput_gain_pct`` row bench_micro gates in ``--check``
and ``run_sanitizers.sh`` drills in CI.

Everything is virtual: chaos ``drop`` actions are pure *triggers* (the
engine sleeps for ``delay``, never for ``drop``) whose magnitudes are
the model constants below, and the journal/controller clock is the
drill's own step clock — so the drill is instant, seeded, and
byte-stable across runs.  No real sockets, threads, or TPUs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu import chaos
from ray_tpu.autopilot import actuators as _actuators
from ray_tpu.autopilot.controller import Autopilot
from ray_tpu.autopilot.journal import Journal
from ray_tpu.observability import comms as _comms
from ray_tpu.observability import goodput as _goodput

#: the fixed seeded schedule — tests golden-assert this exact string so
#: the acceptance run everyone reasons about is the one that executes
DRILL_SEED = 1303
DRILL_CHAOS_SPEC = ("drill.reader@1+=drop;"
                    "drill.collective[rank=1]@1+=drop")

#: virtual workload shape
STEPS = 24               # train steps per arm
TICK_EVERY = 2           # controller tick cadence, in steps
COMPUTE_S = 1.0          # useful compute per step
READER_WAIT_S = 0.4      # host batch assembly stall at prefetch depth 0
TRANSFER_BYTES = 256 * 1024 * 1024   # object traffic per step
STREAM_GBPS = 1.25       # per-stream transport rate (model)
COLLECTIVE_BYTES = 1 * 1024 ** 3     # logical allreduce payload per step
LINK_GBPS = 1.2          # collective wire rate — well under the busbw floor
WORLD_SIZE = 8
SKEW_S = 0.3             # the chaos-delayed rank's rendezvous lateness
CKPT_COST_S = 0.5        # checkpoint overhead charged per save
RESTART_COST_S = 0.0
MISCONFIGURED_CKPT_STEPS = 4     # operator left cadence far too dense
MISCONFIGURED_LINGER_MS = 50.0   # operator left serve linger at the cap
HAZARD_PER_HOUR = 6.0    # fleet hazard feed for the migrated cadence loop

#: compression scheme -> wire-bytes ratio (PR 18 measured block-quant
#: framing: int8 payload + per-block fp32 scales)
WIRE_RATIO = {"none": 1.0, "q8": 0.27, "fp8": 0.145}
HIERARCHY_WIRE_FACTOR = 0.6   # per-host partials keep most bytes on-host
HIERARCHY_SKEW_FACTOR = 0.5   # the late rank only stalls its host group

#: the drill's own knob store — initial (misconfigured / default) values
DRILL_KNOBS = {
    "data_streams_per_peer": 1,
    "fetch_chunk_bytes": 4 * 1024 * 1024,
    "collective_compression": "none",
    "collective_ranks_per_host": 0,
    "data_prefetch_batches": 0,
    "checkpoint_cadence_autopilot_steps": 0,
}
LINGER_KNOB = "serve.drill.linger_ms"


class _Clock:
    """The drill's virtual step clock: the journal, the flap window and
    the decision TTLs all read it, so drill time is the only time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _step_costs(store: Dict[str, Any]) -> Dict[str, float]:
    """One virtual step under the current knobs.  Chaos actions are the
    triggers; the knob values set the magnitudes."""
    prefetch = int(store["data_prefetch_batches"])
    streams = int(store["data_streams_per_peer"])
    scheme = str(store["collective_compression"])
    rph = int(store["collective_ranks_per_host"])

    data_wait = 0.0
    if chaos.inject("drill.reader") == "drop":
        data_wait += READER_WAIT_S / (1.0 + prefetch)
    data_wait += TRANSFER_BYTES / (max(1, streams) * STREAM_GBPS * 1e9)

    wire = COLLECTIVE_BYTES * WIRE_RATIO[scheme]
    if rph > 1:
        wire *= HIERARCHY_WIRE_FACTOR
    collective_wait = wire / (LINK_GBPS * 1e9)
    if chaos.inject("drill.collective", rank="1") == "drop":
        skew = SKEW_S
        if rph > 1:
            skew *= HIERARCHY_SKEW_FACTOR
        collective_wait += skew

    interval = int(store["checkpoint_cadence_autopilot_steps"]) \
        or MISCONFIGURED_CKPT_STEPS
    ckpt_stall = CKPT_COST_S / max(1, interval)

    return {"compute": COMPUTE_S, "data_wait": data_wait,
            "collective_wait": collective_wait, "ckpt_stall": ckpt_stall}


def _window_snapshot(window: List[Dict[str, float]],
                     store: Dict[str, Any]) -> Dict[str, Any]:
    """Render a tick window of step costs into the controller's snapshot
    shape — through the real plane merge math, so the drill exercises
    the same payload contracts the dashboard head serves."""
    cats = {k: 0.0 for k in
            ("compute", "data_wait", "collective_wait", "ckpt_stall")}
    for step in window:
        for k, v in step.items():
            cats[k] += v
    wall = sum(cats.values())
    jobs = _goodput.merge_payloads([
        {"jobs": {"drill": {"wall_s": wall, "cats": cats}}}])

    streams = int(store["data_streams_per_peer"])
    chunk = int(store["fetch_chunk_bytes"])
    xfer_bytes = TRANSFER_BYTES * len(window)
    raw_comms = {
        "groups": {"drill": {
            "world_size": WORLD_SIZE, "seq": len(window), "mismatches": 0,
            "ops": {"allreduce": {
                "count": len(window),
                "bytes": COLLECTIVE_BYTES * len(window),
                "wire_bytes": int(COLLECTIVE_BYTES * len(window)
                                  * WIRE_RATIO[str(
                                      store["collective_compression"])]),
                "seconds": sum(s["collective_wait"] for s in window),
            }},
            "ranks": {},
        }},
        "links": {"drill-a|drill-b": {
            "bytes": xfer_bytes,
            "seconds": xfer_bytes / (max(1, streams) * STREAM_GBPS * 1e9),
            "chunks": max(1, xfer_bytes // max(1, chunk)),
            "retries": 0, "failovers": 0,
        }},
        "recent": [],
    }

    # sparse traffic: requests sit out the full linger window, and the
    # tail picks up scheduling jitter on top of it
    linger = float(store[LINGER_KNOB])
    perf = {"cluster": {"serve.queue_wait": {
        "count": 16.0 * len(window), "mean_ms": linger * 0.8,
        "p50_ms": linger * 0.8, "p95_ms": linger * 1.2,
        "p99_ms": linger * 1.4,
    }, "serve.execute": {
        "count": 16.0 * len(window), "mean_ms": 2.0,
        "p50_ms": 2.0, "p95_ms": 3.0, "p99_ms": 4.0,
    }}}

    return {
        "perf": perf,
        "goodput": {"jobs": jobs},
        "comms": _comms.merge_payloads([raw_comms]),
        "hazard_rate_per_hour": HAZARD_PER_HOUR,
        "cadence_inputs": {"step_cost_s": COMPUTE_S,
                           "ckpt_cost_s": CKPT_COST_S,
                           "restart_cost_s": RESTART_COST_S},
    }


def _dict_actuator(name: str, store: Dict[str, Any], *, kind: str,
                   lo: Optional[float] = None,
                   hi: Optional[float] = None) -> _actuators.Actuator:
    def _get(k=name, s=store):
        return s[k]

    def _set(v, k=name, s=store):
        s[k] = v
    return _actuators.Actuator(name=name, get=_get, set=_set, kind=kind,
                               lo=lo, hi=hi)


def run_arm(autopilot_on: bool) -> Dict[str, Any]:
    """One drill arm under a freshly installed copy of the fixed chaos
    schedule.  Returns the merged goodput, the final knob values, the
    serve queue p95 trajectory and (ON arm) the decision journal."""
    prev_schedule = chaos.schedule()
    chaos.configure(DRILL_SEED, DRILL_CHAOS_SPEC)
    try:
        store: Dict[str, Any] = dict(DRILL_KNOBS)
        store[LINGER_KNOB] = MISCONFIGURED_LINGER_MS
        clock = _Clock()
        reg = _actuators.ActuatorRegistry()
        _actuators.register_config_actuators(reg=reg, store=store)
        reg.register(_dict_actuator(LINGER_KNOB, store, kind="float",
                                    lo=1.0, hi=1000.0))
        journal = Journal(clock=clock)
        pilot = Autopilot(lambda: {}, journal=journal, reg=reg,
                          clock=clock)

        totals = {k: 0.0 for k in
                  ("compute", "data_wait", "collective_wait", "ckpt_stall")}
        window: List[Dict[str, float]] = []
        queue_p95: List[float] = []
        for step in range(1, STEPS + 1):
            costs = _step_costs(store)
            clock.t += sum(costs.values())
            for k, v in costs.items():
                totals[k] += v
            window.append(costs)
            if step % TICK_EVERY == 0:
                snapshot = _window_snapshot(window, store)
                queue_p95.append(float(store[LINGER_KNOB]) * 1.2)
                if autopilot_on:
                    pilot.tick(snapshot)
                window = []

        wall = sum(totals.values())
        merged = _goodput.merge_payloads([
            {"jobs": {"drill": {"wall_s": wall, "cats": totals}}}])
        return {
            "goodput_pct": float(merged["drill"]["goodput_pct"]),
            "wall_s": wall,
            "cats": totals,
            "knobs": dict(store),
            "queue_p95_ms": queue_p95,
            "journal": journal.tail(len(journal.records())),
            "ticks": pilot.ticks,
        }
    finally:
        if prev_schedule is not None:
            chaos.install(prev_schedule)
        else:
            chaos.clear()


def run_ab() -> Dict[str, Any]:
    """The acceptance drill: same workload, same chaos schedule, with
    and without the autopilot.  ``gain_pct`` must be strictly positive
    — bench_micro gates it and run_sanitizers drills it."""
    off = run_arm(autopilot_on=False)
    on = run_arm(autopilot_on=True)
    return {
        "off": off,
        "on": on,
        "gain_pct": on["goodput_pct"] - off["goodput_pct"],
    }
