"""ray_tpu.autopilot — the closed telemetry loop.

The cluster already *measures* everything that matters: the perf plane
times every serve queue and collective quantize, the goodput ledger
attributes every non-compute second, the comms ledger rates every link
and reduction.  Until now a human read those planes on the dashboard
and hand-set the knobs.  The autopilot closes the loop: a per-cluster
controller (hosted by the dashboard head, next to the plane merges it
consumes) that continuously retunes

- serve micro-batch linger from arrival shape and ``queue_wait`` p95,
- ``data_streams_per_peer`` / ``fetch_chunk_bytes`` from the per-peer
  link matrix — the lifelong successor to the one-shot startup probe,
- collective wire compression and hierarchy from ledgered busbw under
  the operator's relative-error budget,
- prefetch depth from the ledger's ``data_wait`` attribution,
- checkpoint cadence from the fleet hazard rate (the PR 17 loop,
  migrated here as the first journaled policy),

all through one guardrailed actuator layer: bounds-clamped, journaled
with the evidence that motivated each change, watched after actuation
and auto-reverted on SLO regression.  ``ray_tpu.doctor --explain
<knob>`` replays the journal; raylint R26 keeps every other runtime
write path off the owned knobs.
"""

from __future__ import annotations

from ray_tpu.autopilot.actuators import (Actuator, ActuatorRegistry, apply,
                                         config_actuator,
                                         register_config_actuators, registry)
from ray_tpu.autopilot.controller import Autopilot
from ray_tpu.autopilot.journal import (APPLIED, CLAMPED, FAILED, REJECTED,
                                       REVERTED, Decision, Journal,
                                       flap_counts, read_from_state)
from ray_tpu.autopilot.knobs import OWNED_KNOBS

__all__ = [
    "Actuator", "ActuatorRegistry", "Autopilot", "Decision", "Journal",
    "OWNED_KNOBS", "APPLIED", "CLAMPED", "FAILED", "REJECTED", "REVERTED",
    "apply", "config_actuator", "flap_counts", "read_from_state",
    "register_config_actuators", "registry",
]
