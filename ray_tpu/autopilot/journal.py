"""The autopilot decision journal.

Every knob change the controller makes — applied, clamped, reverted,
failed, rejected — is one :class:`Decision` record: the evidence
snapshot that motivated it, the old and new values, the guardrail
bounds in force, and a TTL after which the decision no longer claims
the knob.  Records are kept in a bounded in-process ring (always) and
written through the state-service KV under the ``autopilot`` namespace
(when a state client is attached), the same publish-and-read layout the
drain (``drain`` namespace) and preemption (``preempt`` namespace)
planes use — so the doctor can reconstruct *why any knob moved* from
any process that can reach the state service, long after the
controller's process is gone.

KV layout (namespace ``autopilot``)::

    decision:<ts_ms:013d>:<seq:06d>   -> Decision JSON
    knob:<name>                       -> latest Decision JSON for <name>

Keys sort chronologically, so ``kv_keys(prefix=b"decision:")`` replays
the journal in order.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu")

NAMESPACE = b"autopilot"
DECISION_PREFIX = b"decision:"
KNOB_PREFIX = b"knob:"

#: in-process ring capacity — enough for the doctor's flap window at
#: aggressive tick rates without unbounded growth in a long-lived head
RING_CAP = 1024

#: journal record verbs (the ``action`` field)
APPLIED = "applied"      # proposal actuated as-is
CLAMPED = "clamped"      # proposal actuated after guardrail clamp
REVERTED = "reverted"    # post-actuation SLO watch rolled the knob back
FAILED = "failed"        # actuation faulted; previous value restored
REJECTED = "rejected"    # proposal refused outright (bad enum, unknown)


@dataclass
class Decision:
    """One journaled knob change (see module docstring)."""

    knob: str
    old: Any
    new: Any
    action: str = APPLIED
    reason: str = ""
    #: telemetry excerpt that motivated the change — small and JSON-safe
    evidence: Dict[str, Any] = field(default_factory=dict)
    #: guardrail bounds in force: [lo, hi] or the enum choices list
    bounds: Optional[List[Any]] = None
    #: seconds this decision claims the knob before it goes stale
    ttl_s: float = 0.0
    ts: float = 0.0
    seq: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str, sort_keys=True)


class Journal:
    """Bounded in-process decision ring + state-KV write-through.

    ``state`` is a ``StateClient`` (or None for in-process use: unit
    tests, the A/B drill).  Writes never raise — a sick state service
    must not take the controller down with it; the local ring keeps the
    record either way.
    """

    def __init__(self, state: Optional[Any] = None,
                 clock=time.time):
        self._state = state
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: List[Decision] = []  # raylint: guarded-by(self._lock)
        self._seq = 0  # raylint: guarded-by(self._lock)

    def record(self, decision: Decision) -> Decision:
        """Stamp, ring-append and (best-effort) KV-publish one record."""
        with self._lock:
            self._seq += 1
            decision.seq = self._seq
            if not decision.ts:
                decision.ts = float(self._clock())
            self._ring.append(decision)
            del self._ring[:-RING_CAP]
        if self._state is not None:
            payload = decision.to_json().encode()
            key = DECISION_PREFIX + (
                f"{int(decision.ts * 1e3):013d}:{decision.seq:06d}"
                .encode())
            try:
                self._state.kv_put(key, payload, overwrite=True,
                                   namespace=NAMESPACE)
                self._state.kv_put(
                    KNOB_PREFIX + decision.knob.encode(), payload,
                    overwrite=True, namespace=NAMESPACE)
            except Exception as e:  # noqa: BLE001
                logger.debug("autopilot journal: KV publish failed: %s", e)
        return decision

    def records(self, knob: Optional[str] = None) -> List[Decision]:
        with self._lock:
            ring = list(self._ring)
        if knob is None:
            return ring
        return [d for d in ring if d.knob == knob]

    def tail(self, n: int = 50) -> List[Dict[str, Any]]:
        return [asdict(d) for d in self.records()[-n:]]

    def flapping(self, window_s: float, threshold: int = 3,
                 now: Optional[float] = None) -> Dict[str, int]:
        """Knobs that changed >= ``threshold`` times inside the last
        ``window_s`` — the oscillation signal both the controller's
        freeze guard and the doctor's flap flag consume."""
        return flap_counts([asdict(d) for d in self.records()],
                           window_s, threshold, now=now
                           if now is not None else self._clock())


def flap_counts(records: List[Dict[str, Any]], window_s: float,
                threshold: int = 3,
                now: Optional[float] = None) -> Dict[str, int]:
    """Pure flap math over record dicts (journal ring or KV read-back):
    count *actuations* (applied/clamped/reverted) per knob inside the
    window; return knobs at or over the threshold."""
    if now is None:
        now = time.time()
    cutoff = float(now) - float(window_s)
    counts: Dict[str, int] = {}
    for rec in records:
        if rec.get("action") not in (APPLIED, CLAMPED, REVERTED):
            continue
        if float(rec.get("ts") or 0.0) < cutoff:
            continue
        knob = str(rec.get("knob") or "")
        counts[knob] = counts.get(knob, 0) + 1
    return {k: n for k, n in sorted(counts.items()) if n >= threshold}


def read_from_state(state: Any,
                    knob: Optional[str] = None) -> List[Dict[str, Any]]:
    """Replay the journal out of the state KV (chronological — the key
    encoding sorts).  Malformed records are skipped, not fatal: the
    doctor must diagnose with whatever survived."""
    out: List[Dict[str, Any]] = []
    for key in sorted(state.kv_keys(prefix=DECISION_PREFIX,
                                    namespace=NAMESPACE)):
        val = state.kv_get(key, namespace=NAMESPACE)
        if not val:
            continue
        try:
            rec = json.loads(val)
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(rec, dict) and (knob is None
                                      or rec.get("knob") == knob):
            out.append(rec)
    return out
