"""Autopilot policies: telemetry in, knob proposals out.

Every policy is a pure function from the controller's snapshot (the
``/api/perf`` + ``/api/goodput`` + ``/api/comms`` payload shapes, plus
optional cadence inputs) and the current knob values to a list of
*proposals*.  A proposal is a plain dict::

    {"knob": name, "value": proposed, "reason": str,
     "evidence": {...},             # telemetry excerpt, journaled as-is
     "slo": {"kind": ..., ...}}     # what the post-change watch guards

Policies never actuate — the controller routes surviving proposals
through the guardrailed ``actuators.apply()`` path, arms the SLO watch,
and journals the outcome.  Keeping them pure keeps every tuning rule
unit-testable against fixed payloads and keeps the A/B drill honest:
the drill replays these exact functions, not a parallel model.

Policy catalog (the tentpole's four loops + the migrated cadence loop):

- :func:`serve_batch_policy` — shrink a misconfigured serve linger when
  the observed ``serve.queue_wait`` p95 blows the latency budget.
- :func:`transport_policy` — ``fetch_chunk_bytes`` down on failing
  links, ``data_streams_per_peer`` up on healthy saturated links: the
  lifelong successor to the one-shot loopback startup probe.
- :func:`collective_policy` — wire compression (none/q8/fp8) and the
  two-level hierarchy from ledgered busbw, gated by the operator's
  relative-error budget (EQuARX's measured-busbw scheme choice).
- :func:`prefetch_policy` — prefetch depth from the goodput ledger's
  ``data_wait`` attribution.
- :func:`cadence_policy` — the PR 17 hazard->cadence loop, migrated:
  Young-Daly solve from the published fleet hazard rate, actuated as
  the ``checkpoint_cadence_autopilot_steps`` override and journaled
  with its evidence like every other decision.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.config import _config

#: compression scheme -> measured block-quantization relative error
#: (PR 18 accuracy-delta gate measurements; the policy only selects a
#: scheme whose error fits the operator's budget)
SCHEME_REL_ERR = {"none": 0.0, "q8": 1.5e-3, "fp8": 1.2e-2}

Proposal = Dict[str, Any]
Getter = Callable[[str], Any]

_GOODPUT_SLO = {"kind": "goodput_pct"}


def _perf_hist(snapshot: Dict[str, Any], name: str) -> Dict[str, float]:
    return ((snapshot.get("perf") or {}).get("cluster") or {}).get(
        name) or {}


def serve_batch_policy(snapshot: Dict[str, Any], get: Getter,
                       linger_knobs: List[str]) -> List[Proposal]:
    """Serve linger from observed arrival shape: when requests wait in
    the batch queue far longer than they take to execute — the
    signature of a linger window tuned for traffic that is not there —
    halve the linger toward the measured execute time.  One-sided by
    design: growth would trade latency for packing on speculation; the
    decision TTL lets an expired shrink be re-examined instead."""
    queue = _perf_hist(snapshot, "serve.queue_wait")
    execute = _perf_hist(snapshot, "serve.execute")
    q95 = float(queue.get("p95_ms") or 0.0)
    budget_ms = float(_config.get("serve_target_latency_ms"))
    if not queue.get("count") or q95 <= 0.5 * budget_ms:
        return []
    evidence = {"queue_wait_p95_ms": q95,
                "execute_p50_ms": float(execute.get("p50_ms") or 0.0),
                "requests": float(queue.get("count") or 0.0),
                "target_latency_ms": budget_ms}
    out: List[Proposal] = []
    for knob in linger_knobs:
        cur = float(get(knob))
        if cur <= 1.0:
            continue  # already at the floor; nothing left to shrink
        out.append({"knob": knob, "value": max(1.0, cur / 2.0),
                    "reason": f"queue_wait p95 {q95:.1f}ms > 50% of the "
                              f"{budget_ms:.0f}ms latency budget",
                    "evidence": evidence,
                    "slo": {"kind": "perf_p95",
                            "hist": "serve.queue_wait"}})
    return out


def transport_policy(snapshot: Dict[str, Any],
                     get: Getter) -> List[Proposal]:
    """Per-peer link matrix -> stream/chunk tuning.  Failovers mean a
    stream died mid-chunk and its bytes were re-shipped elsewhere:
    smaller chunks bound the blast radius, so halve
    ``fetch_chunk_bytes``.  A clean matrix that still runs more chunks
    than streams can interleave earns one more stream per peer."""
    links = (snapshot.get("comms") or {}).get("links") or {}
    rated = [rec for rec in links.values() if isinstance(rec, dict)]
    if not rated:
        return []
    failovers = sum(int(r.get("failovers") or 0) for r in rated)
    retries = sum(int(r.get("retries") or 0) for r in rated)
    chunks = sum(int(r.get("chunks") or 0) for r in rated)
    secs = sum(float(r.get("seconds") or 0.0) for r in rated)
    gbps = (sum(int(r.get("bytes") or 0) for r in rated) / secs / 1e9
            if secs > 0 else 0.0)
    evidence = {"links": len(rated), "failovers": failovers,
                "retries": retries, "chunks": chunks,
                "aggregate_gbps": round(gbps, 3)}
    out: List[Proposal] = []
    if failovers > 0:
        cur = int(get("fetch_chunk_bytes"))
        if cur > 0:
            out.append({"knob": "fetch_chunk_bytes", "value": cur // 2,
                        "reason": f"{failovers} failover(s) in the link "
                                  "matrix: shrink the re-ship unit",
                        "evidence": evidence, "slo": _GOODPUT_SLO})
    elif retries == 0 and chunks > 0:
        streams = int(get("data_streams_per_peer"))
        # more chunks in flight than streams can interleave: one more
        # lane per peer until the matrix shows stress or the cap
        if streams >= 1 and chunks >= 4 * streams * max(1, len(rated)):
            out.append({"knob": "data_streams_per_peer",
                        "value": streams + 1,
                        "reason": f"{chunks} clean chunks over "
                                  f"{streams} stream(s)/peer: add a lane",
                        "evidence": evidence, "slo": _GOODPUT_SLO})
    return out


def collective_policy(snapshot: Dict[str, Any],
                      get: Getter) -> List[Proposal]:
    """Ledgered busbw + the rel-err budget -> wire scheme/hierarchy.
    A reduction op whose measured busbw sits under the configured floor
    is link-bound: first quantize the wire (q8, then fp8 if the budget
    allows), then decompose hierarchically so only per-host partials
    cross the slow seam."""
    groups = (snapshot.get("comms") or {}).get("groups") or {}
    floor = float(_config.get("autopilot_busbw_floor_gbps"))
    budget = float(_config.get("autopilot_rel_err_budget"))
    worst: Optional[Dict[str, Any]] = None
    for gname, g in sorted(groups.items()):
        for op in ("allreduce", "reducescatter"):
            rec = (g.get("ops") or {}).get(op)
            if not rec or not rec.get("count"):
                continue
            busbw = float(rec.get("busbw_gbps") or 0.0)
            if busbw >= floor:
                continue
            if worst is None or busbw < worst["busbw_gbps"]:
                worst = {"group": gname, "op": op, "busbw_gbps": busbw,
                         "world_size": int(g.get("world_size") or 0),
                         "bytes": int(rec.get("bytes") or 0),
                         "compression_ratio":
                             float(rec.get("compression_ratio") or 1.0)}
    if worst is None:
        return []
    evidence = dict(worst, busbw_floor_gbps=floor, rel_err_budget=budget)
    out: List[Proposal] = []
    scheme = str(get("collective_compression"))
    next_scheme = None
    if scheme == "none" and SCHEME_REL_ERR["q8"] <= budget:
        next_scheme = "q8"
    elif scheme == "q8" and SCHEME_REL_ERR["fp8"] <= budget and \
            worst["busbw_gbps"] < floor / 2.0:
        next_scheme = "fp8"
    if next_scheme is not None:
        out.append({"knob": "collective_compression", "value": next_scheme,
                    "reason": f"{worst['group']}.{worst['op']} busbw "
                              f"{worst['busbw_gbps']:.2f} < "
                              f"{floor:.1f} GB/s and "
                              f"{SCHEME_REL_ERR[next_scheme]:.0e} rel "
                              f"err fits the {budget:.0e} budget",
                    "evidence": evidence, "slo": _GOODPUT_SLO})
    elif scheme != "none":
        # wire already quantized and still slow: cross the seam with
        # per-host partials only
        rph = int(get("collective_ranks_per_host"))
        world = worst["world_size"]
        if rph == 0 and world >= 4 and world % 2 == 0:
            out.append({"knob": "collective_ranks_per_host", "value": 2,
                        "reason": f"{worst['group']}.{worst['op']} still "
                                  f"{worst['busbw_gbps']:.2f} GB/s under "
                                  "a quantized wire: go hierarchical",
                        "evidence": evidence, "slo": _GOODPUT_SLO})
    return out


def prefetch_policy(snapshot: Dict[str, Any],
                    get: Getter) -> List[Proposal]:
    """Prefetch depth from the ledger's ``data_wait`` attribution: a
    step loop that measurably waits on host-side batch assembly gets
    deeper prefetch; a loop that never waits gives depth back (idle
    prefetch threads hold block memory for nothing)."""
    jobs = (snapshot.get("goodput") or {}).get("jobs") or {}
    wall = sum(float(r.get("wall_s") or 0.0) for r in jobs.values())
    data_wait = sum(float((r.get("cats") or {}).get("data_wait") or 0.0)
                    for r in jobs.values())
    if wall <= 0.0:
        return []
    share = data_wait / wall
    cur = int(get("data_prefetch_batches"))
    evidence = {"data_wait_s": round(data_wait, 3),
                "wall_s": round(wall, 3),
                "data_wait_share": round(share, 4)}
    if share > 0.10:
        return [{"knob": "data_prefetch_batches", "value": cur + 2,
                 "reason": f"data_wait is {share:.0%} of wall",
                 "evidence": evidence, "slo": _GOODPUT_SLO}]
    if share < 0.01 and cur > 0:
        return [{"knob": "data_prefetch_batches", "value": cur - 1,
                 "reason": f"data_wait is {share:.1%} of wall: give a "
                           "prefetch slot back",
                 "evidence": evidence, "slo": _GOODPUT_SLO}]
    return []


def cadence_policy(snapshot: Dict[str, Any],
                   get: Getter) -> List[Proposal]:
    """The migrated PR 17 hazard->cadence loop.  Same Young-Daly solver
    (:func:`ray_tpu.checkpoint.cadence.solve_interval_steps`), but the
    decision now flows through the actuator layer: solved from the
    fleet hazard rate the autoscaler publishes plus the measured
    step/checkpoint costs, journaled with that evidence, actuated as
    the ``checkpoint_cadence_autopilot_steps`` override every
    ``CadenceController`` consults before its own local solve."""
    from ray_tpu.checkpoint.cadence import solve_interval_steps
    hazard = snapshot.get("hazard_rate_per_hour")
    inputs = snapshot.get("cadence_inputs") or {}
    step_s = float(inputs.get("step_cost_s") or 0.0)
    ckpt_s = float(inputs.get("ckpt_cost_s") or 0.0)
    if hazard is None or step_s <= 0.0:
        return []  # no hazard feed or no step clock: keep local control
    hazard = float(hazard)
    interval = solve_interval_steps(
        hazard, step_s, ckpt_s,
        restart_cost_s=float(inputs.get("restart_cost_s") or 0.0))
    cur = int(get("checkpoint_cadence_autopilot_steps"))
    if interval == cur:
        return []
    return [{"knob": "checkpoint_cadence_autopilot_steps",
             "value": interval,
             "reason": f"Young-Daly at {hazard:.2f} preemptions/h",
             "evidence": {"hazard_rate_per_hour": hazard,
                          "step_cost_s": step_s, "ckpt_cost_s": ckpt_s,
                          "restart_cost_s":
                              float(inputs.get("restart_cost_s") or 0.0),
                          "solved_interval_steps": interval},
             "slo": _GOODPUT_SLO}]


def propose(snapshot: Dict[str, Any], get: Getter,
            actuator_names: List[str]) -> List[Proposal]:
    """Run every policy whose actuators are registered; proposals for
    unregistered knobs are dropped here, not at apply time."""
    names = set(actuator_names)
    linger = sorted(n for n in names
                    if n.startswith("serve.") and n.endswith(".linger_ms"))
    proposals: List[Proposal] = []
    proposals += serve_batch_policy(snapshot, get, linger)
    proposals += transport_policy(snapshot, get)
    proposals += collective_policy(snapshot, get)
    proposals += prefetch_policy(snapshot, get)
    proposals += cadence_policy(snapshot, get)
    return [p for p in proposals if p["knob"] in names]
