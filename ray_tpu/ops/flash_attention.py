"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

The MXU-resident attention kernel used by the model stack. Blocks of Q stay
in VMEM while K/V blocks stream through; softmax is computed online
(running max + normalizer in VMEM scratch) so the O(L²) score matrix never
hits HBM. Causal masking skips fully-masked K blocks at the grid level.
The backward pass recomputes P from the saved log-sum-exp (flash-style
rematerialization) in two kernels: one accumulating dQ over K blocks, one
accumulating dK/dV over Q blocks.

Falls back to interpreter mode off-TPU so the same code path is exercised by
the CPU test mesh. Role in the stack: the per-shard kernel under
``ray_tpu.parallel.sequence.ring_attention`` and the dense-attention op for
``ray_tpu.models`` (the reference delegates attention to torch; here it is a
first-class TPU kernel).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # stats buffers keep a full lane dim (TPU tiling)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # raylint: allow(swallow) capability probe: no jax backend
        return False


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int, seq_k: int):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: K blocks strictly above the diagonal contribute nothing.
    run = (ik * block_k < (iq + 1) * block_q) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                      # [bq, d]
        k = k_ref[0].astype(jnp.float32)                      # [bk, d]
        v = v_ref[0].astype(jnp.float32)                      # [bk, d]
        # Pad rows of a ragged last K block hold garbage (possibly NaN/Inf);
        # zero them so 0-weighted dot contributions stay 0 (0*NaN = NaN).
        kv_valid = (ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        k = jnp.where(kv_valid, k, 0.0)
        v = jnp.where(kv_valid, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = cols < seq_k  # ragged last K block must not leak pad columns
        if causal:
            mask = jnp.logical_and(mask, rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, :1]                                 # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)          # [bq, bk]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lse is stored compact [BH, Lq, 1]: same column orientation as the
        # scratch stats, single lane (Mosaic allows full-dim lane blocks).
        lse_ref[0] = m_scr[:, :1] + jnp.log(l)                # [bq, 1]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    nq = pl.cdiv(Lq, block_q)
    nk = pl.cdiv(Lk, block_k)
    grid = (BH, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk, seq_k=Lk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr, *, scale, causal, block_q, block_k, num_k_blocks,
               seq_k):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ik * block_k < (iq + 1) * block_q) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                      # [bq, 1]
        delta = delta_ref[0]                                  # [bq, 1]
        kv_valid = (ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        k = jnp.where(kv_valid, k, 0.0)
        v = jnp.where(kv_valid, v, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = cols < seq_k
        if causal:
            mask = jnp.logical_and(mask, rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(mask, p * (dp - delta) * scale, 0.0)
        acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                block_q, block_k, num_q_blocks, seq_k, seq_q):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (ik * block_k < (iq + 1) * block_q) if causal else (iq >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                      # [bq, 1]
        delta = delta_ref[0]                                  # [bq, 1]
        # Pad *query* rows of a ragged last Q block would contaminate the
        # dk/dv sums (they reduce over q rows); zero the sources and mask p.
        q_valid = (iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < seq_q
        q = jnp.where(q_valid, q, 0.0)
        do = jnp.where(q_valid, do, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = jnp.logical_and(cols < seq_k, rows < seq_q)
        if causal:
            mask = jnp.logical_and(mask, rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)             # [bq, bk]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(mask, p * (dp - delta) * scale, 0.0)    # [bq, bk]
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, d]

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    do = g
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    nq = pl.cdiv(Lq, block_q)
    nk = pl.cdiv(Lk, block_k)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                   # [BH, Lq]
    lse_c = lse[:, :, None]                                    # [BH, Lq, 1]
    delta_c = delta[:, :, None]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_k=Lk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_c, delta_c)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          seq_k=Lk, seq_q=Lq),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_c, delta_c)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhld(q, k, v, scale, causal, block_q, block_k,
                          interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_rule(scale, causal, block_q, block_k, interpret, residuals, g):
    return _flash_bwd(scale, causal, block_q, block_k, interpret, residuals, g)


_flash_attention_bhld.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention. q/k/v: [batch, seqlen, heads, head_dim].

    Returns [batch, seqlen, heads, head_dim]. Differentiable (custom VJP).
    """
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = not _on_tpu()
    # [B, L, H, D] -> [B*H, L, D]
    qb = q.transpose(0, 2, 1, 3).reshape(B * H, Lq, D)
    kb = k.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    vb = v.transpose(0, 2, 1, 3).reshape(B * H, Lk, D)
    out = _flash_attention_bhld(qb, kb, vb, scale, causal, block_q, block_k,
                                interpret)
    return out.reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
