from ray_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
