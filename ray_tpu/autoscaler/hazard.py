"""Preemption-hazard estimation for the elastic preemptible fleet.

Preemptible capacity is cheap because the provider may reclaim any node
with ~``preempt_lead_s`` of notice.  PR 8 made that survivable (the
notice starts a graceful drain); this module makes it *plannable*: every
``"preemption notice"`` drain is journaled into the state-service KV, and
the :class:`HazardEstimator` folds that history into a per-node hazard
score the autoscaler acts on **before** the next notice lands — a
proactive drain gets the full ``drain_deadline_s`` budget instead of the
provider's eviction lead.

KV layout (namespace ``preempt``; the journal is the cross-process
analogue of the ``drain`` namespace's progress records):

======================  ====================================================
key                     value (JSON)
======================  ====================================================
``event:<ts_ms>:<nid>`` one observed preemption notice: ``{"ts", "node",
                        "node_type", "reason"}`` — written by the drain
                        orchestrator when the drain reason carries
                        ``"preemption notice"``; pruned past
                        ``hazard_window_s``
``probe:<nid>``         the node's preemption-probe health: ``{"failures":
                        consecutive probe errors, "ts"}`` — written by the
                        host daemon's watcher, flagged by the doctor
``fleet:rate``          the estimator's published fleet hazard rate
                        (decayed preemptions/hour): ``{"rate_per_hour",
                        "ts"}`` — the cadence solver's risk input
======================  ====================================================

Hazard math — all pure functions, unit-tested in isolation:

- an event of age ``a`` contributes ``0.5 ** (a / hazard_halflife_s)``;
- a node type's rate is the decayed event count divided by the decay's
  mean lifetime (``halflife / ln 2``), in events/hour;
- a node's hazard is its type rate plus ``hazard_probe_weight`` per
  consecutive probe failure (a blind watcher may never see the real
  notice, so the node must be treated as riskier, not safer).
"""

from __future__ import annotations

import json
import logging
import math
import time
from typing import Dict, Iterable, List, Optional

from ray_tpu._private.config import _config

logger = logging.getLogger("ray_tpu")

#: State-KV namespace shared by the journal, the probe-health records and
#: the published fleet rate.
NAMESPACE = b"preempt"
EVENT_PREFIX = b"event:"
PROBE_PREFIX = b"probe:"
FLEET_RATE_KEY = b"fleet:rate"


def decayed_rate_per_hour(ages_s: Iterable[float], halflife_s: float,
                          window_s: float) -> float:
    """Events/hour from a list of event ages, exponentially decayed.

    Each event inside ``window_s`` contributes ``0.5 ** (age/halflife)``;
    the decayed count is normalized by the decay's mean lifetime
    (``halflife / ln 2``) so one *fresh* event at half-life ``h`` reads
    as roughly ``3600 * ln2 / h`` events/hour.  Monotone in both inputs:
    more events ⇒ higher, fresher events ⇒ higher.
    """
    halflife_s = max(1.0, halflife_s)
    weight = sum(0.5 ** (age / halflife_s) for age in ages_s
                 if 0.0 <= age <= window_s)
    mean_lifetime_s = halflife_s / math.log(2)
    return weight * 3600.0 / mean_lifetime_s


def node_hazard_score(type_rate_per_hour: float, probe_failures: int = 0,
                      probe_weight: Optional[float] = None) -> float:
    """Fold the node type's historical rate and the node's probe health
    into one score (still in events/hour units)."""
    if probe_weight is None:
        probe_weight = _config.get("hazard_probe_weight")
    return type_rate_per_hour + probe_weight * max(0, int(probe_failures))


def journal_preemption(state, node_id_hex: str, node_type: str,
                       reason: str, ts: Optional[float] = None) -> None:
    """Append one observed preemption notice to the KV journal.

    Called by the drain orchestrator (``begin_drain``) when the drain
    reason carries ``"preemption notice"`` — i.e. only *real* notices
    (chaos or metadata probe) are history; proactive hazard drains are
    not, else the estimator would feed on its own output."""
    ts = time.time() if ts is None else ts
    key = EVENT_PREFIX + f"{int(ts * 1e3):015d}:{node_id_hex}".encode()
    record = {"ts": ts, "node": node_id_hex,
              "node_type": node_type or "default", "reason": reason}
    state.kv_put(key, json.dumps(record).encode(), namespace=NAMESPACE)


def publish_probe_health(state, node_id_hex: str, failures: int) -> None:
    """Publish a node's consecutive preempt-probe failure count (host
    daemon's watcher; read back by the estimator and the doctor)."""
    record = {"failures": int(failures), "ts": time.time()}
    state.kv_put(PROBE_PREFIX + node_id_hex.encode(),
                 json.dumps(record).encode(), namespace=NAMESPACE)


def read_fleet_rate(state) -> Optional[float]:
    """The last published fleet hazard rate, or None (never published /
    state unreachable). Callers fall back to hazard_rate_floor_per_hour."""
    try:
        raw = state.kv_get(FLEET_RATE_KEY, namespace=NAMESPACE)
        if not raw:
            return None
        return float(json.loads(raw)["rate_per_hour"])
    except Exception as e:  # noqa: BLE001
        logger.debug("hazard: fleet rate read failed: %s", e)
        return None


class HazardEstimator:
    """Per-node-type preemption hazard from the KV journal.

    ``state`` is a StateClient (or None for a purely local estimator fed
    via :meth:`record` — the in-process runtime has no KV).  ``refresh()``
    re-reads the journal and garbage-collects events past the window;
    the autoscaler calls it once per reconciliation pass.
    """

    def __init__(self, state=None):
        self._state = state
        # [(ts, node_type, node_hex)] inside the window, newest last.
        self._events: List[tuple] = []
        self._probe_failures: Dict[str, int] = {}

    # ------------------------------------------------------------- intake

    def record(self, node_type: str, node_id_hex: str = "",
               ts: Optional[float] = None) -> None:
        """Feed one preemption event directly (tests / in-proc runtime)."""
        self._events.append((time.time() if ts is None else ts,
                             node_type or "default", node_id_hex))

    def refresh(self, now: Optional[float] = None) -> None:
        """Re-read the journal; prune (and KV-GC) events past the window."""
        now = time.time() if now is None else now
        window = _config.get("hazard_window_s")
        if self._state is not None:
            try:
                self._load_from_kv(now, window)
            except Exception as e:  # noqa: BLE001
                logger.debug("hazard: KV refresh failed (keeping last "
                             "view): %s", e)
        self._events = [e for e in self._events if now - e[0] <= window]

    def _load_from_kv(self, now: float, window: float) -> None:
        events: List[tuple] = []
        for key in self._state.kv_keys(prefix=EVENT_PREFIX,
                                       namespace=NAMESPACE):
            raw = self._state.kv_get(key, namespace=NAMESPACE)
            if not raw:
                continue
            try:
                rec = json.loads(raw)
                ts = float(rec["ts"])
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
            if now - ts > window:
                # The journal outlives any one estimator; GC keeps the
                # namespace bounded at window-worth of events.
                self._state.kv_del(key, namespace=NAMESPACE)
                continue
            events.append((ts, rec.get("node_type") or "default",
                           rec.get("node") or ""))
        probes: Dict[str, int] = {}
        for key in self._state.kv_keys(prefix=PROBE_PREFIX,
                                       namespace=NAMESPACE):
            raw = self._state.kv_get(key, namespace=NAMESPACE)
            if not raw:
                continue
            try:
                probes[key[len(PROBE_PREFIX):].decode()] = int(
                    json.loads(raw).get("failures") or 0)
            except (ValueError, UnicodeDecodeError):
                continue
        events.sort()
        self._events = events
        self._probe_failures = probes

    # ------------------------------------------------------------- scores

    def type_rate(self, node_type: str, now: Optional[float] = None) -> float:
        """Decayed preemptions/hour observed for one node type."""
        now = time.time() if now is None else now
        ages = [now - ts for ts, t, _ in self._events
                if t == (node_type or "default")]
        return decayed_rate_per_hour(ages,
                                     _config.get("hazard_halflife_s"),
                                     _config.get("hazard_window_s"))

    def node_hazard(self, node_type: str, node_id_hex: str = "",
                    now: Optional[float] = None) -> float:
        """Per-node hazard: the type's historical rate plus the node's
        probe-blindness penalty."""
        return node_hazard_score(
            self.type_rate(node_type, now=now),
            self._probe_failures.get(node_id_hex, 0))

    def fleet_rate(self, now: Optional[float] = None) -> float:
        """Fleet-wide decayed preemptions/hour (all types), floored at
        ``hazard_rate_floor_per_hour`` so a cold fleet still plans with
        the provider's advertised rate."""
        now = time.time() if now is None else now
        ages = [now - ts for ts, _t, _n in self._events]
        rate = decayed_rate_per_hour(ages,
                                     _config.get("hazard_halflife_s"),
                                     _config.get("hazard_window_s"))
        return max(rate, _config.get("hazard_rate_floor_per_hour"))

    def publish_fleet_rate(self, now: Optional[float] = None) -> float:
        """Write the current fleet rate to the KV for the cadence solver
        (no-op without a state client). Returns the rate either way."""
        rate = self.fleet_rate(now=now)
        if self._state is not None:
            try:
                self._state.kv_put(
                    FLEET_RATE_KEY,
                    json.dumps({"rate_per_hour": rate,
                                "ts": time.time() if now is None
                                else now}).encode(),
                    namespace=NAMESPACE)
            except Exception as e:  # noqa: BLE001
                logger.debug("hazard: fleet rate publish failed: %s", e)
        return rate
