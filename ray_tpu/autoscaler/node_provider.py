"""Node provider plugin API + the in-process fake provider.

Parity with ``python/ray/autoscaler/node_provider.py`` (the abstract
cloud-provider interface every deployment implements) and
``fake_multi_node/node_provider.py:237`` (nodes simulated in-process,
used by ``test_autoscaler_fake_multinode.py``). A real TPU provider
would call the GKE/queued-resources API to obtain pod slices; the
interface is deliberately identical so that swap is config-only.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal lifecycle interface (create/terminate/list)."""

    def __init__(self, provider_config: Optional[dict] = None):
        self.provider_config = provider_config or {}

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        raise NotImplementedError

    def node_type(self, provider_node_id: str) -> str:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Launches nodes into a live in-process ``Runtime``.

    ``node_types`` maps type name -> resource dict, e.g.
    ``{"tpu-v4-8": {"CPU": 8, "TPU": 4}}``.
    """

    def __init__(self, runtime, node_types: Dict[str, Dict[str, float]]):
        super().__init__()
        self._runtime = runtime
        self._node_types = dict(node_types)
        self._lock = threading.Lock()
        self._nodes: Dict[str, Any] = {}   # provider id -> runtime Node
        self._types: Dict[str, str] = {}

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [pid for pid, node in self._nodes.items() if node.alive]

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        from ray_tpu._private.resources import ResourceSet
        if node_type not in self._node_types:
            raise ValueError(f"unknown node type {node_type!r}")
        created = []
        for _ in range(count):
            node = self._runtime.add_node(
                ResourceSet(dict(self._node_types[node_type])),
                labels={"autoscaler-node-type": node_type})
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            with self._lock:
                self._nodes[pid] = node
                self._types[pid] = node_type
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_node_id, None)
            self._types.pop(provider_node_id, None)
        if node is not None:
            self._runtime.remove_node(node.node_id)

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        with self._lock:
            t = self._types.get(provider_node_id)
        return dict(self._node_types.get(t, {}))

    def node_type(self, provider_node_id: str) -> str:
        with self._lock:
            return self._types[provider_node_id]

    def runtime_node_id(self, provider_node_id: str):
        with self._lock:
            return self._nodes[provider_node_id].node_id
