"""TPU pod-slice node provider (GCP queued-resources / GKE).

Parity with ``python/ray/autoscaler/_private/gcp/node_provider.py``: the
cloud half of the autoscaler. A "node" here is a whole TPU pod slice
(e.g. ``v5litepod-8``) obtained through the Cloud TPU queued-resources
API; its startup script launches ``ray-tpu start --address=<head>`` so
the slice's host daemon joins the cluster when the resource turns ACTIVE.

All cloud interaction goes through a pluggable ``command_runner`` (the
``gcloud`` CLI by default) so the provider is fully testable offline —
tests inject a fake runner that simulates PROVISIONING -> ACTIVE
transitions; production uses the real CLI with no code change (same
swap-by-config philosophy as the reference's provider registry).
"""

from __future__ import annotations

import json
import shlex
import subprocess
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

# queued-resource states that count as "not terminated"
_LIVE_STATES = {"ACCEPTED", "PROVISIONING", "CREATING", "ACTIVE",
                "WAITING_FOR_RESOURCES"}


def _gcloud_runner(args: List[str]) -> str:
    """Default command runner: the gcloud CLI. Raises on failure."""
    proc = subprocess.run(["gcloud"] + args, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"gcloud {' '.join(map(shlex.quote, args))} failed: "
            f"{proc.stderr.strip()[:500]}")
    return proc.stdout


class TPUPodSliceProvider(NodeProvider):
    """Provisions/terminates TPU pod slices via Cloud TPU queued resources.

    ``provider_config``::

        {
          "project": "my-project",
          "zone": "us-central2-b",
          "runtime_version": "tpu-ubuntu2204-base",
          "cluster_address": "head-host:6379",   # state service to join
          "node_types": {
            "v5e-8":  {"accelerator_type": "v5litepod-8",
                       "resources": {"CPU": 208, "TPU": 8}},
            "v5e-16": {"accelerator_type": "v5litepod-16",
                       "resources": {"CPU": 416, "TPU": 16}},
          },
        }
    """

    def __init__(self, provider_config: Optional[dict] = None,
                 command_runner: Optional[Callable[[List[str]], str]] = None):
        super().__init__(provider_config)
        cfg = self.provider_config
        for req in ("project", "zone", "node_types"):
            if req not in cfg:
                raise ValueError(f"TPU provider config missing {req!r}")
        self._run = command_runner or _gcloud_runner
        self._lock = threading.Lock()
        self._types: Dict[str, str] = {}  # qr id -> node type

    # -- helpers ---------------------------------------------------------
    def _scope(self) -> List[str]:
        cfg = self.provider_config
        return [f"--project={cfg['project']}", f"--zone={cfg['zone']}"]

    def _startup_script(self) -> str:
        addr = self.provider_config.get("cluster_address", "")
        if not addr:
            return ""
        # an authenticated cluster (the default) rejects tokenless joins;
        # the slice must present the head-minted secret
        token = self.provider_config.get("auth_token", "")
        export = (f"export RAY_TPU_AUTH_TOKEN={shlex.quote(token)}\n"
                  if token else "")
        return (f"#! /bin/bash\n{export}"
                f"python -m ray_tpu.scripts.cluster start "
                f"--address={addr} --block &\n")

    # -- NodeProvider ----------------------------------------------------
    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        cfg = self.provider_config
        spec = cfg["node_types"].get(node_type)
        if spec is None:
            raise ValueError(f"unknown node type {node_type!r}; "
                             f"configured: {sorted(cfg['node_types'])}")
        created = []
        for _ in range(count):
            qr_id = f"raytpu-{node_type}-{uuid.uuid4().hex[:8]}"
            args = ["compute", "tpus", "queued-resources", "create", qr_id,
                    f"--node-id={qr_id}",
                    f"--accelerator-type={spec['accelerator_type']}",
                    f"--runtime-version="
                    f"{cfg.get('runtime_version', 'tpu-ubuntu2204-base')}",
                    *self._scope()]
            script = self._startup_script()
            if script:
                args.append(f"--metadata=startup-script={script}")
            if cfg.get("spot"):
                args.append("--spot")
            self._run(args)
            with self._lock:
                self._types[qr_id] = node_type
            created.append(qr_id)
        return created

    def non_terminated_nodes(self) -> List[str]:
        out = self._run(["compute", "tpus", "queued-resources", "list",
                         "--format=json", *self._scope()])
        live = []
        for entry in json.loads(out or "[]"):
            name = entry.get("name", "").rsplit("/", 1)[-1]
            state = (entry.get("state", {}) or {}).get("state", "")
            if state in _LIVE_STATES and name.startswith("raytpu-"):
                live.append(name)
                with self._lock:
                    # rediscover type for nodes created by a previous
                    # autoscaler incarnation: encoded in the id
                    if name not in self._types:
                        parts = name.split("-")
                        if len(parts) >= 3:
                            self._types[name] = "-".join(parts[1:-1])
        return live

    def terminate_node(self, provider_node_id: str) -> None:
        self._run(["compute", "tpus", "queued-resources", "delete",
                   provider_node_id, "--force", "--quiet", *self._scope()])
        with self._lock:
            self._types.pop(provider_node_id, None)

    def node_resources(self, provider_node_id: str) -> Dict[str, float]:
        with self._lock:
            t = self._types.get(provider_node_id)
        spec = self.provider_config["node_types"].get(t, {})
        return dict(spec.get("resources", {}))

    def node_type(self, provider_node_id: str) -> str:
        with self._lock:
            return self._types[provider_node_id]
