"""The autoscaling control loop.

Parity with ``StandardAutoscaler.update``
(``autoscaler/_private/autoscaler.py:147,336``): read demand from
``LoadMetrics``, bin-pack unmet demand onto the cheapest feasible node
types (``resource_demand_scheduler.py``'s role), launch within
``max_workers``, terminate nodes idle past ``idle_timeout_s``. Driven
either manually (tests call ``update()``) or by ``start()``'s monitor
thread (the head-side ``Monitor`` process, ``monitor.py:125``).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger("ray_tpu")


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, Dict[str, float]] = field(default_factory=dict)
    max_workers: int = 10
    min_workers: int = 0
    max_workers_per_type: Dict[str, int] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    update_interval_s: float = 5.0
    upscaling_speed: float = 1.0  # max new nodes = max(1, speed * current)


class LoadMetrics:
    """Demand + utilization snapshot from the runtime (reference:
    ``load_metrics.py`` fed by GCS resource-usage reports)."""

    def __init__(self, runtime):
        self._runtime = runtime

    def pending_demands(self) -> List[Dict[str, float]]:
        return self._runtime.pending_resource_demands()

    def node_utilization(self) -> Dict[str, dict]:
        """node hex id -> {"total": .., "available": .., "idle": bool}.

        DRAINING/DRAINED nodes are excluded along with dead ones: a
        draining node that has quiesced *looks* idle, but terminating it
        mid-drain would turn a graceful migration into a node death; and
        its capacity is about to leave, so bin-packing unmet demand onto
        it would mask a needed scale-up."""
        out = {}
        for ns in self._runtime.node_states():
            if not ns.alive or ns.draining:
                continue
            total = ns.resources.total.to_dict()
            avail = ns.resources.available.to_dict()
            idle = all(avail.get(k, 0.0) >= v for k, v in total.items())
            out[ns.node_id.hex()] = {
                "total": total, "available": avail, "idle": idle}
        return out

    def lifecycle(self) -> Dict[str, dict]:
        """node hex id -> {"alive": .., "draining": ..} for every node the
        runtime knows (the gang-replacement scan needs the nodes
        ``node_utilization`` deliberately hides)."""
        return {ns.node_id.hex(): {"alive": ns.alive,
                                   "draining": ns.draining}
                for ns in self._runtime.node_states()}


def _fits(demand: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 runtime=None, hazard=None):
        if runtime is None:
            from ray_tpu._private import worker as _worker
            runtime = _worker.global_worker().runtime
        self.config = config
        self.provider = provider
        self.load_metrics = LoadMetrics(runtime)
        self._runtime = runtime
        # Infeasible tasks must queue (as demand) rather than fail fast.
        runtime.autoscaling_enabled = True
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0
        # Elastic preemptible fleet: the hazard estimator feeds proactive
        # drains and pending-drain placement hints; gang replacement
        # refills every drain. A distributed runtime's state client backs
        # the estimator with the cluster-wide KV journal; the in-process
        # runtime gets a local (record()-fed) estimator.
        if hazard is None:
            from ray_tpu.autoscaler.hazard import HazardEstimator
            hazard = HazardEstimator(getattr(runtime, "state", None))
        self.hazard = hazard
        self._replaced: set = set()      # provider ids already refilled
        self.num_replacements = 0
        self.num_proactive_drains = 0

    # -- one reconciliation pass (autoscaler.py:336 update) ---------------

    def update(self) -> Dict[str, int]:
        drained = self._hazard_pass()
        replaced = self._gang_replace()
        launched = self._scale_up()
        terminated = self._scale_down()
        return {"launched": launched, "terminated": terminated,
                "proactively_drained": drained, "replaced": replaced}

    # -- preemption hazard: predict, hint, proactively drain ---------------

    def _provider_runtime_ids(self) -> Dict[str, str]:
        """provider id -> runtime node hex, for nodes already registered."""
        out = {}
        for pid in self.provider.non_terminated_nodes():
            try:
                out[pid] = self.provider.runtime_node_id(pid).hex()
            except (AttributeError, KeyError) as e:
                logger.debug("autoscaler: node %s has no runtime id yet "
                             "(%s); skipping hazard scan", pid, e)
        return out

    def _hazard_pass(self) -> int:
        """Refresh the estimator, hint high-hazard nodes as last-choice
        placements, and proactively drain the highest-hazard node once
        its score crosses ``hazard_drain_threshold`` — ahead of the real
        notice, so the drain runs with the full ``drain_deadline_s``
        budget instead of ``preempt_lead_s``."""
        from ray_tpu._private.config import _config
        self.hazard.refresh()
        self.hazard.publish_fleet_rate()
        lifecycle = self.load_metrics.lifecycle()
        place_thresh = _config.get("hazard_placement_threshold")
        drain_thresh = _config.get("hazard_drain_threshold")
        hint = getattr(self._runtime, "set_pending_drain", None)
        # At most one proactive drain in flight: the hazard rate is a
        # per-TYPE signal, so without this guard every node of a hot type
        # would cross the threshold and the fleet would cascade-drain
        # itself one pass at a time.
        draining_now = any(st["alive"] and st["draining"]
                           for st in lifecycle.values())
        worst: Optional[tuple] = None   # (score, pid, rid)
        for pid, rid in self._provider_runtime_ids().items():
            state = lifecycle.get(rid)
            if state is None or not state["alive"] or state["draining"]:
                continue
            score = self.hazard.node_hazard(self.provider.node_type(pid),
                                            rid)
            if hint is not None:
                hint(rid, score >= place_thresh)
            if score >= drain_thresh and (worst is None
                                          or score > worst[0]):
                worst = (score, pid, rid)
        if (worst is None or draining_now
                or not _config.get("hazard_proactive_drains")):
            return 0
        score, pid, rid = worst
        logger.warning("autoscaler: proactive drain of %s (hazard %.2f "
                       ">= %.2f)", rid[:8], score, drain_thresh)
        if not self._drain_runtime_node(rid, reason=(
                f"preemption hazard {score:.2f} (proactive)")):
            return 0
        self.num_proactive_drains += 1  # raylint: allow(data-race) single autoscaler update loop is the only writer; counter is monitoring-only
        return 1

    def _drain_runtime_node(self, rid_hex: str, reason: str) -> bool:
        """Start a graceful drain with the full drain budget: through the
        state service on a distributed runtime, or by flipping the node's
        lifecycle flag on the in-process runtime (which has no drain
        orchestrator — the node just stops taking new placements)."""
        from ray_tpu._private.config import _config
        state = getattr(self._runtime, "state", None)
        if state is not None:
            try:
                state.drain_node(bytes.fromhex(rid_hex), reason,
                                 deadline_s=_config.get("drain_deadline_s"))
                return True
            except Exception as e:  # noqa: BLE001
                logger.warning("autoscaler: proactive drain of %s failed: "
                               "%s", rid_hex[:8], e)
                return False
        from ray_tpu._private.ids import NodeID
        node = getattr(self._runtime, "nodes", {}).get(
            NodeID(bytes.fromhex(rid_hex)))
        if node is None:
            return False
        node.draining = True
        self._runtime._kick()
        return True

    # -- gang replacement: every drain is refilled same-type ---------------

    def _gang_replace(self) -> int:
        """Launch a same-type replacement for every provider node that is
        draining (or died out from under us) — immediately, not when the
        drained node's capacity shortfall shows up as unmet demand, so
        the replacement daemon gang-joins while the drain is still
        migrating and the job reshards onto a full-size world."""
        lifecycle = self.load_metrics.lifecycle()
        ids = self._provider_runtime_ids()
        stable = sum(1 for pid, rid in ids.items()
                     if (st := lifecycle.get(rid)) is not None
                     and st["alive"] and not st["draining"])
        replaced = 0
        for pid, rid in ids.items():
            st = lifecycle.get(rid)
            if st is None or (st["alive"] and not st["draining"]):
                continue
            if pid in self._replaced:
                continue
            if stable + replaced >= self.config.max_workers:
                logger.warning("autoscaler: not replacing draining node "
                               "%s (at max_workers=%d)", rid[:8],
                               self.config.max_workers)
                break
            ntype = self.provider.node_type(pid)
            self.provider.create_node(ntype, 1)
            self._replaced.add(pid)  # raylint: allow(data-race) only touched inside update() — the single monitor loop, or a test driving update() directly with no monitor running
            replaced += 1
            logger.info("autoscaler: gang replacement for %s (%s)",
                        rid[:8], ntype)
        self.num_replacements += replaced  # raylint: allow(data-race) single autoscaler update loop is the only writer; counter is monitoring-only
        return replaced

    def _unmet_demands(self) -> List[Dict[str, float]]:
        """Demands that no live node could satisfy even when empty."""
        demands = self.load_metrics.pending_demands()
        if not demands:
            return []
        node_totals = [u["total"] for u in
                       self.load_metrics.node_utilization().values()]
        return [d for d in demands
                if not any(_fits(d, t) for t in node_totals)]

    def _scale_up(self) -> int:
        unmet = self._unmet_demands()
        current = self.provider.non_terminated_nodes()
        budget = self.config.max_workers - len(current)
        if budget <= 0 and len(current) >= self.config.min_workers:
            if not unmet:
                return 0
        min_needed = max(0, self.config.min_workers - len(current))
        # min_workers is a hard floor — not throttled by upscaling_speed.
        launch_cap = max(1, min_needed,
                         int(self.config.upscaling_speed
                             * max(1, len(current))))
        to_launch: Dict[str, int] = {}
        # Ensure min_workers of the first declared type.
        if len(current) < self.config.min_workers and self.config.node_types:
            first = next(iter(self.config.node_types))
            to_launch[first] = self.config.min_workers - len(current)
        # Bin-pack each unmet demand onto the smallest feasible type
        # (types are assumed declared small->large, reference sorts by
        # resources; we sort by total resource sum).
        types_sorted = sorted(
            self.config.node_types.items(),
            key=lambda kv: sum(kv[1].values()))
        for demand in unmet:
            for tname, tres in types_sorted:
                if _fits(demand, tres):
                    cap = self.config.max_workers_per_type.get(
                        tname, self.config.max_workers)
                    already = sum(
                        1 for pid in current
                        if self.provider.node_type(pid) == tname)
                    if already + to_launch.get(tname, 0) < cap:
                        to_launch[tname] = to_launch.get(tname, 0) + 1
                    break
        launched = 0
        for tname, count in to_launch.items():
            count = min(count,
                        self.config.max_workers - len(current) - launched,
                        launch_cap - launched)
            if count <= 0:
                continue
            self.provider.create_node(tname, count)
            launched += count
        self.num_launches += launched  # raylint: allow(data-race) single autoscaler update loop is the only writer; counter is monitoring-only
        return launched

    def _scale_down(self) -> int:
        util = self.load_metrics.node_utilization()
        now = time.monotonic()
        current = self.provider.non_terminated_nodes()
        terminated = 0
        for pid in current:
            if len(current) - terminated <= self.config.min_workers:
                break
            try:
                rid = self.provider.runtime_node_id(pid).hex()
            except (AttributeError, KeyError) as e:
                logger.debug("autoscaler: node %s has no runtime id yet "
                             "(%s); skipping idle check", pid, e)
                continue
            info = util.get(rid)
            if info is None or not info["idle"]:
                self._idle_since.pop(pid, None)  # raylint: allow(data-race) single autoscaler update loop is the only mutator of idle tracking
                continue
            first_idle = self._idle_since.setdefault(pid, now)  # raylint: allow(data-race) single autoscaler update loop is the only mutator of idle tracking
            if now - first_idle >= self.config.idle_timeout_s:
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)  # raylint: allow(data-race) single autoscaler update loop is the only mutator of idle tracking
                terminated += 1
        self.num_terminations += terminated  # raylint: allow(data-race) single autoscaler update loop is the only writer; counter is monitoring-only
        return terminated

    # -- monitor thread (reference: Monitor process, monitor.py:125) ------

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler-monitor")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self.update()
            except Exception:  # pragma: no cover — monitor must survive
                import logging
                logging.getLogger("ray_tpu").exception("autoscaler update")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Restore fail-fast for infeasible tasks: nothing will grow the
        # cluster anymore, so queued-forever would hang callers.
        self._runtime.autoscaling_enabled = False  # raylint: allow(data-race) monitor thread already joined above; no concurrent reader remains
        self._runtime._kick()
