"""Autoscaling: demand-driven node launch/terminate.

Parity with ``python/ray/autoscaler/`` (``StandardAutoscaler``
``_private/autoscaler.py:147``, ``LoadMetrics``, the pluggable
``NodeProvider`` API ``node_provider.py``, and the in-process
``FakeMultiNodeProvider`` ``_private/fake_multi_node/node_provider.py:237``
used by CI). The TPU deployment target is pod slices: a provider models
node types like ``tpu-v4-8`` host groups; the fake provider adds/removes
nodes of the in-process runtime for tests.
"""

from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig, LoadMetrics,
                                           StandardAutoscaler)
from ray_tpu.autoscaler.hazard import HazardEstimator
from ray_tpu.autoscaler.node_provider import (FakeNodeProvider, NodeProvider)

__all__ = ["StandardAutoscaler", "AutoscalerConfig", "LoadMetrics",
           "NodeProvider", "FakeNodeProvider", "HazardEstimator"]
