"""``@ray_tpu.remote`` task wrapper.

Parity with ``python/ray/remote_function.py`` (``RemoteFunction._remote``
:231, ``.options()`` :214-228) and the decorator in ``worker.py:2747``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.ids import TaskID
from ray_tpu._private.resources import resources_from_options
from ray_tpu._private.task_spec import TaskOptions, TaskSpec
from ray_tpu.object_ref import ObjectRef


class RemoteFunction:
    def __init__(self, function: Callable, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._default_options = options or {}
        functools.update_wrapper(self, function)

    def options(self, **updates) -> "RemoteFunction":
        merged = dict(self._default_options)
        merged.update(updates)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__name__} cannot be called "
            "directly; use .remote()")

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: remote_function.py:219-226)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def _remote(self, args, kwargs, opts: Dict[str, Any]):
        from ray_tpu._private import worker as _worker
        w = _worker.global_worker()
        task_opts = _build_task_options(opts)
        spec = TaskSpec(
            task_id=TaskID.for_task(w.runtime.job_id),
            job_id=w.runtime.job_id,
            function=self._function,
            function_name=opts.get("name") or self._function.__qualname__,
            args=tuple(args),
            kwargs=dict(kwargs),
            options=task_opts,
        )
        return_ids = w.runtime.submit_task(spec)
        refs = [ObjectRef(rid, owner=w.runtime) for rid in return_ids]
        if task_opts.num_returns == 1:
            return refs[0]
        if task_opts.num_returns == 0:
            return None
        return refs


def _build_task_options(opts: Dict[str, Any]) -> TaskOptions:
    resources = resources_from_options(
        num_cpus=opts.get("num_cpus"),
        num_tpus=opts.get("num_tpus"),
        num_gpus=opts.get("num_gpus"),
        memory=opts.get("memory"),
        resources=opts.get("resources"),
        default_cpus=1.0,
    )
    pg = opts.get("placement_group")
    scheduling_strategy = opts.get("scheduling_strategy", "DEFAULT")
    return TaskOptions(
        num_returns=opts.get("num_returns", 1),
        resources=resources,
        max_retries=opts.get("max_retries", 3),
        retry_exceptions=opts.get("retry_exceptions", False),
        scheduling_strategy=scheduling_strategy,
        placement_group=pg,
        placement_group_bundle_index=opts.get(
            "placement_group_bundle_index", -1),
        name=opts.get("name"),
        runtime_env=opts.get("runtime_env"),
    )


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` decorator for
    functions and classes (reference ``worker.py:2747``)."""
    from ray_tpu.actor import ActorClass

    def _make(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        if callable(target):
            return RemoteFunction(target, options)
        raise TypeError(f"@remote target must be function or class, got {target}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return _make(args[0], {})
    if args:
        raise TypeError("@remote options must be keyword arguments")

    def decorator(target):
        return _make(target, dict(kwargs))

    return decorator
