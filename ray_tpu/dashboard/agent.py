"""Per-node reporter agent.

Parity with ``dashboard/agent.py:51`` + ``dashboard/modules/reporter``
(the psutil sampler): a daemon thread inside each host daemon samples
process + host stats from ``/proc`` (no psutil dependency) and publishes
them into the state service KV under namespace ``node_stats``, keyed by
node id. The dashboard head aggregates the blobs; entries carry a
timestamp so the head can mark stale reporters.
"""

from __future__ import annotations
import logging

import json
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("ray_tpu")

_NS = b"node_stats"


def _read_proc_self_cpu_ticks() -> int:
    """utime+stime of this process, in clock ticks."""
    with open("/proc/self/stat") as f:
        parts = f.read().split()
    return int(parts[13]) + int(parts[14])


def _read_rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def _read_meminfo() -> Dict[str, float]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(v.split()[0]) / 1024.0  # MiB
    except OSError:
        pass
    return out


class NodeReporterAgent:
    """Samples this daemon's process + host stats and publishes to the
    state-service KV. One per host daemon; started by ``host_daemon`` and
    stopped with the runtime."""

    def __init__(self, runtime, interval_s: float = 2.0):
        self.runtime = runtime
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_ticks = _read_proc_self_cpu_ticks()
        self._last_ts = time.monotonic()
        self._clk = os.sysconf("SC_CLK_TCK")

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-reporter")
        self._thread.start()

    def stop(self):
        self._stop.set()

    def sample(self) -> Dict[str, Any]:
        now = time.monotonic()
        ticks = _read_proc_self_cpu_ticks()
        dt = max(1e-6, now - self._last_ts)
        cpu_pct = 100.0 * (ticks - self._last_ticks) / self._clk / dt
        self._last_ticks, self._last_ts = ticks, now
        stats: Dict[str, Any] = {
            "ts": time.time(),
            "pid": os.getpid(),
            "cpu_percent": round(cpu_pct, 1),
            "rss_mb": round(_read_rss_mb(), 1),
            "load_avg": list(os.getloadavg()),
            "mem": _read_meminfo(),
        }
        rt = self.runtime
        try:
            store = rt.local_node.store
            stats["object_store"] = {
                "num_objects": len(getattr(store, "_entries", {})),
            }
        except Exception as e:
            logger.debug("object-store stats failed: %s", e)
        arena = getattr(rt, "host_arena", None)
        if arena is not None:
            try:
                used, cap, count = arena.stats()
                stats["arena"] = {"used_mb": round(used / 1048576, 1),
                                  "capacity_mb": round(cap / 1048576, 1),
                                  "objects": count,
                                  "owner": rt._arena_is_owner}
            except Exception as e:
                logger.debug("arena stats failed: %s", e)
        try:
            avail = rt.local_node.resources.available.to_dict()
            total = rt.local_node.resources.total.to_dict()
            stats["resources"] = {"available": avail, "total": total}
        except Exception as e:
            logger.debug("resource stats failed: %s", e)
        monitor = getattr(rt, "memory_monitor", None)
        if monitor is not None:
            try:
                stats["memory_monitor"] = monitor.snapshot()
            except Exception as e:
                logger.debug("memory-monitor stats failed: %s", e)
        try:
            from ray_tpu.observability import recorder as _flight
            rec = _flight.get_recorder()
            if rec is not None:
                report = _flight.disk_report()
                stats["flight_recorder"] = {
                    "dir": rec.dir,
                    "recordings": len(report["recordings"]),
                    "sealed_bundles": len(report["bundles"]),
                }
        except Exception as e:
            logger.debug("flight-recorder stats failed: %s", e)
        return stats

    def publish_once(self):
        stats = self.sample()
        self.runtime.state.kv_put(
            self.runtime.local_node.node_id.binary(),
            json.dumps(stats).encode(), namespace=_NS)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.publish_once()
            except Exception as e:
                logger.debug("stats publish failed: %s", e)
                if self._stop.is_set():
                    return


def collect_node_stats(state_client) -> Dict[str, Dict[str, Any]]:
    """Head-side aggregation: node_id hex -> latest reporter blob."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        for key in state_client.kv_keys(namespace=_NS):
            blob = state_client.kv_get(key, namespace=_NS)
            if blob:
                try:
                    out[key.hex()] = json.loads(blob)
                except ValueError:
                    pass
    except Exception as e:
        logger.debug("cluster stats read failed: %s", e)
    return out
