"""Dashboard head: HTTP UI + JSON API over the state service.

Parity with ``dashboard/head.py:63`` / ``state_aggregator.py``: a single
HTTP server that renders cluster state. Everything is read live from the
C++ state service (tables + the ``node_stats`` reporter KV), so the head
can run in the driver, on the head node, or standalone against any
cluster address — it holds no state of its own.

Endpoints:
  /                 — self-contained HTML UI (polls the JSON API)
  /api/cluster      — nodes + reporter stats + resource totals
  /api/actors       — actor table
  /api/actor?id=X   — one actor's full record (drill-down)
  /api/pgs          — placement groups
  /api/jobs         — job table
  /api/stats        — state-service counters
  /api/node_debug?node=X&lines=N&tasks=1&trace=T
                    — per-daemon log tail + local task rows, fetched
                      live from the daemon over NODE_DEBUG (the log
                      viewer / task drill-down the reference serves via
                      dashboard/modules/log/log_agent.py); ``trace=T``
                      filters the log tail to one trace id
  /api/timeline     — merged chrome://tracing timeline: every alive
                      daemon's span ring (GET_TIMELINE fan-out) plus the
                      head's own, distinct pids per host. Partial
                      failures degrade, not error: hosts that could not
                      be reached are listed in ``missing_hosts``
  /api/trace?id=X   — one distributed trace's spans + instant events,
                      filtered out of the merged timeline
  /api/metrics      — per-host metric snapshots (NODE_DEBUG
                      include_metrics fan-out), JSON keyed by node,
                      with unreachable hosts in ``missing_hosts``
  /metrics          — the same federation rendered as one cluster-wide
                      Prometheus exposition, each sample labeled with
                      its source node; unreachable hosts surface as
                      ``federation_missing_hosts`` samples
  /api/perf         — perf-plane latency quantiles: per-node and
                      cluster-merged count/mean/p50/p95/p99 for every
                      perf histogram (rpc/task/fetch/ckpt/serve/...),
                      exact merge of the raw bucket counts riding the
                      metric federation
  /api/goodput      — goodput ledger federation: per-node per-job
                      wall-clock attribution (compute/compile/data_wait/
                      collective_wait/ckpt_stall/restart_downtime/idle)
                      merged into per-job category totals +
                      ``goodput_pct``, degrading with ``missing_hosts``
  /api/comms        — comms-plane federation: per-node collective
                      ledgers (per-group op bytes/duration/algbw/busbw,
                      per-rank arrival-skew histograms, fingerprint
                      mismatches, the StripedTransfer link matrix)
                      merged exactly, plus derived laggard-rank skew
                      flags and link outliers
  /api/profile?host=X&seconds=N
                    — federated sampling-profiler output (collapsed
                      stacks + pprof-shaped JSON). seconds=0 returns
                      cumulative profiles; seconds>0 diffs two
                      snapshots that far apart (the window's samples)
  /api/forensics    — cluster-wide crash forensics: every alive
                      daemon's live thread stacks, in-flight tasks and
                      on-disk flight recordings / sealed crash bundles
                      (NODE_DEBUG include_stacks+include_bundles
                      fan-out) plus the head's own — the wire the
                      health doctor (``python -m ray_tpu.doctor``)
                      collects through
"""

from __future__ import annotations
import logging

import json
import threading
import time
import urllib.parse
from typing import Optional

from ray_tpu.dashboard.agent import collect_node_stats

logger = logging.getLogger("ray_tpu")

_PAGE = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa;color:#222}
h1{font-size:20px} h2{font-size:15px;margin-top:28px;color:#444}
table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
th,td{padding:6px 10px;border-bottom:1px solid #eee;text-align:left;font-size:13px}
th{background:#f0f0f3;font-weight:600}
.dead{color:#b00} .alive{color:#080}
#updated{color:#888;font-size:12px}
</style></head><body>
<h1>ray_tpu cluster <span id=updated></span></h1>
<h2>Nodes</h2><table id=nodes></table>
<h2>Actors</h2><table id=actors></table>
<h2>Placement groups</h2><table id=pgs></table>
<h2>Jobs</h2><table id=jobs></table>
<h2>Node drill-down</h2>
<div>
  <select id=nodesel></select>
  <button onclick="drill()">fetch logs + tasks</button>
</div>
<h2 style="font-size:13px">Tasks on node</h2><table id=ntasks></table>
<h2 style="font-size:13px">Recent logs</h2>
<pre id=nlogs style="background:#111;color:#ddd;padding:10px;max-height:320px;overflow:auto;font-size:12px"></pre>
<script>
// all dynamic values are escaped: actor/class/label names are
// user-controlled and must not inject HTML into the viewer's page
function esc(v){return String(v).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function row(cells, tag){tag=tag||'td';return '<tr>'+cells.map(c=>'<'+tag+'>'+c+'</'+tag+'>').join('')+'</tr>'}
function rowe(cells, tag){return row(cells.map(esc), tag)}
async function refresh(){
  const c = await (await fetch('/api/cluster')).json();
  let h = row(['node','address','state','CPU','TPU','cpu%','rss MB','host mem','arena','objects'],'th');
  const sel = document.getElementById('nodesel');
  const cur = sel.value; sel.innerHTML='';
  for (const n of c.nodes){
    const s = n.stats||{}; const a = s.arena||{}; const mm = s.memory_monitor||{};
    const mem = mm.total_mb ? (mm.used_frac*100).toFixed(0)+'%'+(mm.over_threshold?' OOM-GUARD':'') :
      (s.mem&&s.mem.MemTotal ? (100*(1-s.mem.MemAvailable/s.mem.MemTotal)).toFixed(0)+'%' : '-');
    h += row([esc(n.node_id.slice(0,8)), esc(n.address),
      '<span class="'+(n.alive?'alive':'dead')+'">'+(n.alive?'ALIVE':'DEAD')+'</span>',
      esc((n.available.CPU??0)+'/'+(n.total.CPU??0)),
      esc((n.available.TPU??'-')+'/'+(n.total.TPU??'-')),
      esc(s.cpu_percent??'-'), esc(s.rss_mb??'-'), esc(mem),
      esc(a.capacity_mb? a.used_mb+'/'+a.capacity_mb+' MB'+(a.owner?' (owner)':'') : '-'),
      esc((s.object_store||{}).num_objects??'-')]);
    if (n.alive){
      const o = document.createElement('option');
      o.value = n.node_id; o.textContent = n.node_id.slice(0,8)+' @ '+n.address;
      sel.appendChild(o);
    }
  }
  if (cur) sel.value = cur;
  document.getElementById('nodes').innerHTML = h;
  const actors = await (await fetch('/api/actors')).json();
  let ah = row(['actor','class','state','node','restarts',''],'th');
  for (const x of actors) ah += row([esc(x.actor_id.slice(0,8)), esc(x.class_name),
    esc(x.state), esc((x.node_id||'').slice(0,8)), esc(x.num_restarts??0),
    '<a href="/api/actor?id='+encodeURIComponent(x.actor_id)+'" target=_blank>detail</a>']);
  document.getElementById('actors').innerHTML = ah;
  const pgs = await (await fetch('/api/pgs')).json();
  let ph = row(['pg','strategy','state','bundles'],'th');
  for (const p of pgs) ph += rowe([p.pg_id.slice(0,8), p.strategy, p.state, p.num_bundles]);
  document.getElementById('pgs').innerHTML = ph;
  const jobs = await (await fetch('/api/jobs')).json();
  let jh = row(['job','driver','state'],'th');
  for (const j of jobs) jh += rowe([j.job_id, j.driver_address, j.state]);
  document.getElementById('jobs').innerHTML = jh;
  document.getElementById('updated').textContent = 'updated '+new Date().toLocaleTimeString();
}
async function drill(){
  const nid = document.getElementById('nodesel').value;
  if (!nid) return;
  const d = await (await fetch('/api/node_debug?node='+encodeURIComponent(nid)+'&lines=200&tasks=1')).json();
  if (d.error){ document.getElementById('nlogs').textContent = d.error; return; }
  let th = row(['task','name','state'],'th');
  for (const t of (d.tasks||[])) th += rowe([t.task_id.slice(0,8), t.name, t.state]);
  document.getElementById('ntasks').innerHTML = th;
  document.getElementById('nlogs').textContent = (d.logs||[]).join('\\n') || '(no recent log lines)';
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class DashboardHead:
    """Serves the UI + API against one cluster's state service."""

    def __init__(self, state_addr: str, port: int = 0,
                 host: str = "127.0.0.1"):
        from ray_tpu._private.rpc import ConnectionPool
        from ray_tpu._private.state_client import StateClient
        self.state = StateClient(state_addr)
        self.pool = ConnectionPool()  # daemon connections for drill-down
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._host, self._want_port = host, port
        self.port: Optional[int] = None
        #: the cluster's closed-loop controller (ray_tpu/autopilot/),
        #: hosted here next to the plane merges it consumes; started by
        #: start() when autopilot_enabled — after the serve thread is
        #: already up, so the handoff needs a real guard
        self._autopilot_lock = threading.Lock()
        # raylint: guarded-by(self._autopilot_lock)
        self.autopilot = None

    # -- API payloads ----------------------------------------------------
    def _cluster(self) -> dict:
        stats = collect_node_stats(self.state)
        nodes = []
        for n in self.state.list_nodes():
            nid = n.node_id.hex()
            nodes.append({
                "node_id": nid,
                "address": n.address,
                "alive": n.alive,
                "state": (n.state or ("ALIVE" if n.alive else "DEAD")),
                "drain_reason": n.drain_reason,
                "is_head": n.is_head,
                "total": dict(n.total.amounts),
                "available": dict(n.available.amounts),
                "labels": dict(n.labels),
                "death_reason": n.death_reason,
                "stats": stats.get(nid),
            })
        return {"ts": time.time(), "nodes": nodes}

    def _actors(self) -> list:
        return [{
            "actor_id": a.actor_id.hex(),
            "class_name": a.class_name,
            "state": a.state,
            "node_id": a.node_id.hex() if a.node_id else "",
            "name": a.name,
            "num_restarts": a.restart_count,
        } for a in self.state.list_actors()]

    def _pgs(self) -> list:
        return [{
            "pg_id": p.pg_id.hex(),
            "strategy": p.strategy,
            "state": p.state,
            "num_bundles": len(p.bundles),
        } for p in self.state.list_pgs()]

    def _jobs(self) -> list:
        return [{
            "job_id": j.job_id.hex(),
            "driver_address": j.driver_address,
            "state": j.state,
        } for j in self.state.list_jobs()]

    def _actor_detail(self, actor_id_hex: str) -> dict:
        for a in self.state.list_actors():
            if a.actor_id.hex() == actor_id_hex:
                return {
                    "actor_id": a.actor_id.hex(),
                    "class_name": a.class_name,
                    "state": a.state,
                    "node_id": a.node_id.hex() if a.node_id else "",
                    "address": a.address,
                    "name": a.name,
                    "namespace": a.namespace,
                    "num_restarts": a.restart_count,
                    "death_cause": getattr(a, "death_cause", ""),
                }
        return {"error": f"actor {actor_id_hex} not found"}

    def _node_debug(self, node_id_hex: str, lines: int,
                    include_tasks: bool, trace_filter: str = "") -> dict:
        from ray_tpu.protocol import pb
        addr = next((n.address for n in self.state.list_nodes()
                     if n.node_id.hex() == node_id_hex and n.alive), None)
        if addr is None:
            return {"error": f"node {node_id_hex} not alive"}
        client = self.pool.get(addr)
        rep = pb.NodeDebugReply()
        rep.ParseFromString(client.call(
            pb.NODE_DEBUG, pb.NodeDebugRequest(
                log_lines=lines,
                include_tasks=include_tasks,
                trace_filter=trace_filter).SerializeToString(),
            timeout=15).body)
        out = json.loads(bytes(rep.payload_json).decode())
        out["node_id"] = node_id_hex
        out["address"] = addr
        return out

    # -- tracing / metrics federation ------------------------------------
    def _alive_addrs(self) -> list:
        return [(n.node_id.hex(), n.address)
                for n in self.state.list_nodes() if n.alive and n.address]

    def _timeline(self) -> dict:
        """One merged chrome://tracing event list: the head's own span
        ring plus every alive daemon's, pulled over GET_TIMELINE. Hosts
        keep distinct ``pid`` labels so the merged view separates them.
        A daemon that is registered alive but unreachable (dying, net
        partition) degrades into ``missing_hosts`` instead of failing
        the whole merge."""
        from ray_tpu.protocol import pb
        from ray_tpu._private.profiling import get_profiler
        events = list(get_profiler().chrome_trace())
        missing = []
        for nid, addr in self._alive_addrs():
            try:
                rep = pb.TimelineReply()
                rep.ParseFromString(self.pool.get(addr).call(
                    pb.GET_TIMELINE,
                    pb.TimelineRequest().SerializeToString(),
                    timeout=30).body)
                events.extend(json.loads(bytes(rep.spans_json).decode()))
            except Exception as e:
                logger.debug("dashboard: timeline fetch from %s failed: %s",
                             addr, e)
                missing.append({"node_id": nid, "address": addr,
                                "error": str(e)})
        return {"traceEvents": events, "missing_hosts": missing}

    def _trace(self, trace_id: str) -> dict:
        from ray_tpu import observability
        if not trace_id:
            return {"error": "missing ?id=<trace_id>"}
        merged = self._timeline()
        events = observability.spans_for_trace(
            trace_id, merged["traceEvents"])
        events.sort(key=lambda e: e.get("ts", 0))
        return {"trace_id": trace_id, "num_events": len(events),
                "events": events,
                "missing_hosts": merged["missing_hosts"]}

    def _metric_snapshots(self) -> "tuple[dict, list]":
        """({node_label: metrics.snapshot()}, missing_hosts) across the
        cluster — the head's own registry plus each alive daemon's via
        NODE_DEBUG. Unreachable daemons land in ``missing_hosts``."""
        from ray_tpu.protocol import pb
        from ray_tpu.util import metrics as _metrics
        snaps = {"head": _metrics.snapshot()}
        missing = []
        for nid, addr in self._alive_addrs():
            try:
                rep = pb.NodeDebugReply()
                rep.ParseFromString(self.pool.get(addr).call(
                    pb.NODE_DEBUG, pb.NodeDebugRequest(
                        log_lines=0, include_tasks=False,
                        include_metrics=True).SerializeToString(),
                    timeout=15).body)
                payload = json.loads(bytes(rep.payload_json).decode())
                snaps[f"node:{nid[:8]}"] = payload.get("metrics") or []
            except Exception as e:
                logger.debug("dashboard: metrics fetch from %s failed: %s",
                             addr, e)
                missing.append({"node_id": nid, "address": addr,
                                "error": str(e)})
        return snaps, missing

    # -- perf plane ------------------------------------------------------
    def _perf(self) -> dict:
        """Cluster latency quantiles: per-node and cluster-merged
        count/mean/p50/p95/p99 per perf histogram, computed from the raw
        bucket counts that ride the federated metric snapshots (the
        ``"perf"`` payload in each ``raytpu_perf_*`` family). The merge
        is exact — same bucket layout everywhere, counts just add."""
        from ray_tpu.observability import perf as perf_mod
        snaps, missing = self._metric_snapshots()
        nodes = {}
        agg: dict = {}
        for node, fams in snaps.items():
            per = {}
            for name, p in perf_mod.extract_perf(fams).items():
                counts = [int(c) for c in p["counts"]]
                sum_ms = float(p.get("sum_ms", 0.0))
                bounds = p.get("bounds")
                per[name] = perf_mod.summarize(counts, sum_ms, bounds)
                a = agg.setdefault(name, {"counts": [], "sum_ms": 0.0,
                                          "bounds": bounds})
                a["counts"] = perf_mod.merge_counts([a["counts"], counts])
                a["sum_ms"] += sum_ms
            if per:
                nodes[node] = per
        cluster = {name: perf_mod.summarize(a["counts"], a["sum_ms"],
                                            a["bounds"])
                   for name, a in agg.items()}
        return {"ts": time.time(), "nodes": nodes, "cluster": cluster,
                "missing_hosts": missing}

    # -- goodput ledger --------------------------------------------------
    def _goodput(self) -> dict:
        """Cluster goodput: each node's per-job wall-clock attribution
        ledger (the ``"goodput"`` payload riding the federated metric
        snapshots) merged into per-job category totals + ``goodput_pct``
        (recomputed from merged seconds, never averaged from per-node
        percentages). Per-node ledgers stay visible for skew triage;
        unreachable daemons degrade into ``missing_hosts``."""
        from ray_tpu.observability import goodput as goodput_mod
        snaps, missing = self._metric_snapshots()
        nodes = {}
        for node, fams in snaps.items():
            payload = goodput_mod.extract_goodput(fams)
            if payload and payload.get("jobs"):
                nodes[node] = payload["jobs"]
        jobs = goodput_mod.merge_payloads(
            {"jobs": per} for per in nodes.values())
        return {"ts": time.time(),
                "categories": list(goodput_mod.CATEGORIES),
                "jobs": jobs, "nodes": nodes, "missing_hosts": missing}

    # -- comms ledger ----------------------------------------------------
    def _comms(self) -> dict:
        """Cluster comms plane: each node's collective ledger (the
        ``"comms"`` payload riding the federated metric snapshots)
        merged exactly — bytes/seconds/bucket-counts add, bandwidths
        recomputed from the sums — plus the derived attribution the CLI
        and doctor consume: laggard-rank skew flags and link-matrix
        outliers. Per-node ledgers stay visible; unreachable daemons
        degrade into ``missing_hosts``."""
        from ray_tpu.observability import comms as comms_mod
        snaps, missing = self._metric_snapshots()
        nodes = {}
        for node, fams in snaps.items():
            payload = comms_mod.extract_comms(fams)
            if payload:
                nodes[node] = payload
        merged = comms_mod.merge_payloads(nodes.values())
        return {"ts": time.time(),
                "groups": merged["groups"], "links": merged["links"],
                "recent": merged["recent"], "bounds": merged["bounds"],
                "skew_flags": comms_mod.skew_flags(
                    merged["groups"], bounds=merged["bounds"]),
                "link_flags": comms_mod.link_flags(merged["links"]),
                "nodes": nodes, "missing_hosts": missing}

    # -- autopilot -------------------------------------------------------
    def _autopilot_snapshot(self) -> dict:
        """The controller's tick input: the same three plane merges the
        dashboard already serves, taken in one sweep."""
        return {"perf": self._perf(), "goodput": self._goodput(),
                "comms": self._comms()}

    def _start_autopilot(self) -> None:
        from ray_tpu._private.config import _config
        if not _config.get("autopilot_enabled"):
            return
        from ray_tpu.autopilot import actuators as _actuators
        from ray_tpu.autopilot.controller import Autopilot
        from ray_tpu.autopilot.journal import Journal

        def _hazard():
            from ray_tpu.autoscaler import hazard as _hz
            return _hz.read_fleet_rate(self.state)

        with self._autopilot_lock:
            if self.autopilot is not None:
                return
            _actuators.register_config_actuators()
            self.autopilot = Autopilot(
                self._autopilot_snapshot,
                journal=Journal(state=self.state),
                hazard_fn=_hazard)
            self.autopilot.start()
        logger.info("autopilot: controller started in dashboard head")

    def _autopilot_payload(self) -> dict:
        with self._autopilot_lock:
            ap = self.autopilot
        if ap is None:
            from ray_tpu.autopilot import journal as _journal
            # controller not hosted here: serve the journal from the KV
            # so a read-only head can still explain the knobs
            try:
                tail = _journal.read_from_state(self.state)[-50:]
            except Exception as e:  # noqa: BLE001 — state KV may be gone
                logger.debug("autopilot journal read failed: %s", e)
                tail = []
            return {"ts": time.time(), "enabled": False, "journal": tail}
        status = ap.status()
        status.update({"ts": time.time(), "enabled": True})
        return status

    def _profile_snapshots(self, host: str = "") -> "tuple[dict, list]":
        """({host_label: cumulative profile}, missing) — the head's own
        sampler plus each alive daemon's (NODE_DEBUG include_stacks
        carries ``payload["profile"]``). ``host`` filters by label
        prefix ("head", "node:ab12cd34", or a node-id prefix)."""
        from ray_tpu.protocol import pb
        from ray_tpu.observability import sampler as _sampler
        out = {}
        missing = []

        def _want(label):
            return (not host or label.startswith(host)
                    or label.startswith(f"node:{host}"))

        if _want("head"):
            prof = _sampler.profile_snapshot()
            if prof is not None:
                out["head"] = prof
        for nid, addr in self._alive_addrs():
            label = f"node:{nid[:8]}"
            if not _want(label):
                continue
            try:
                rep = pb.NodeDebugReply()
                rep.ParseFromString(self.pool.get(addr).call(
                    pb.NODE_DEBUG, pb.NodeDebugRequest(
                        log_lines=0, include_tasks=False,
                        include_stacks=True).SerializeToString(),
                    timeout=15).body)
                payload = json.loads(bytes(rep.payload_json).decode())
                prof = payload.get("profile")
                if prof:
                    out[label] = prof
            except Exception as e:
                logger.debug("dashboard: profile fetch from %s failed: %s",
                             addr, e)
                missing.append({"node_id": nid, "address": addr,
                                "error": str(e)})
        return out, missing

    def _profile(self, host: str = "", seconds: float = 0.0) -> dict:
        """Federated sampling profile. ``seconds=0`` returns cumulative
        profiles (since each sampler started); ``seconds>0`` takes two
        cumulative snapshots that far apart and returns the window's
        difference — no wire support needed beyond the cumulative
        fetch. Response carries collapsed-stack text (flamegraph.pl
        input) and pprof-shaped JSON of the cross-host merge."""
        from ray_tpu.observability import sampler as _sampler
        first, missing = self._profile_snapshots(host)
        hosts = first
        if seconds > 0:
            time.sleep(min(float(seconds), 60.0))
            second, missing = self._profile_snapshots(host)
            hosts = {label: _sampler.diff_profiles(p, first.get(label, {}))
                     for label, p in second.items()}
        merged = _sampler.merge_profiles(list(hosts.values()))
        return {"ts": time.time(), "seconds": seconds, "hosts": hosts,
                "merged": merged,
                "collapsed": _sampler.collapsed(merged),
                "pprof": _sampler.pprof_json(merged),
                "missing_hosts": missing}

    def _forensics(self) -> dict:
        """Cluster-wide crash forensics, the doctor's collection wire:
        per-node live thread stacks, in-flight task registry, and the
        on-disk flight-recorder report (recordings + sealed bundles),
        plus the head process's own. Dead/unreachable nodes degrade
        into ``missing_hosts`` — their story lives in the bundles the
        surviving daemons sealed for them."""
        from ray_tpu.protocol import pb
        from ray_tpu.observability import recorder as _flight
        nodes = {}
        missing = []
        for nid, addr in self._alive_addrs():
            try:
                rep = pb.NodeDebugReply()
                rep.ParseFromString(self.pool.get(addr).call(
                    pb.NODE_DEBUG, pb.NodeDebugRequest(
                        log_lines=0, include_tasks=True,
                        include_stacks=True,
                        include_bundles=True).SerializeToString(),
                    timeout=15).body)
                payload = json.loads(bytes(rep.payload_json).decode())
                payload["address"] = addr
                nodes[nid] = payload
            except Exception as e:
                logger.debug("dashboard: forensics fetch from %s failed: %s",
                             addr, e)
                missing.append({"node_id": nid, "address": addr,
                                "error": str(e)})
        return {
            "ts": time.time(),
            "head": {
                "stacks": _flight.thread_stacks(),
                "inflight": _flight.inflight_snapshot(),
                "forensics": _flight.disk_report(),
            },
            "nodes": nodes,
            "missing_hosts": missing,
        }

    # -- server ----------------------------------------------------------
    def start(self) -> int:
        import http.server
        head = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, payload, code: int = 200):
                self._send(json.dumps(payload, default=str).encode(),
                           "application/json", code)

            def do_GET(self):
                try:
                    parsed = urllib.parse.urlparse(self.path)
                    q = urllib.parse.parse_qs(parsed.query)
                    route = parsed.path
                    if route in ("/", "/index.html"):
                        self._send(_PAGE.encode(), "text/html")
                    elif route == "/api/cluster":
                        self._json(head._cluster())
                    elif route == "/api/actors":
                        self._json(head._actors())
                    elif route == "/api/actor":
                        self._json(head._actor_detail(
                            q.get("id", [""])[0]))
                    elif route == "/api/pgs":
                        self._json(head._pgs())
                    elif route == "/api/jobs":
                        self._json(head._jobs())
                    elif route == "/api/stats":
                        self._json(head.state.stats())
                    elif route == "/api/node_debug":
                        self._json(head._node_debug(
                            q.get("node", [""])[0],
                            int(q.get("lines", ["200"])[0]),
                            q.get("tasks", ["1"])[0] not in ("0", ""),
                            q.get("trace", [""])[0]))
                    elif route == "/api/timeline":
                        self._json(head._timeline())
                    elif route == "/api/trace":
                        self._json(head._trace(q.get("id", [""])[0]))
                    elif route == "/api/metrics":
                        snaps, missing = head._metric_snapshots()
                        self._json({"snapshots": snaps,
                                    "missing_hosts": missing})
                    elif route == "/api/perf":
                        self._json(head._perf())
                    elif route == "/api/goodput":
                        self._json(head._goodput())
                    elif route == "/api/comms":
                        self._json(head._comms())
                    elif route == "/api/autopilot":
                        self._json(head._autopilot_payload())
                    elif route == "/api/profile":
                        self._json(head._profile(
                            q.get("host", [""])[0],
                            float(q.get("seconds", ["0"])[0])))
                    elif route == "/api/forensics":
                        self._json(head._forensics())
                    elif route == "/metrics":
                        from ray_tpu.util.metrics import render_federated
                        snaps, missing = head._metric_snapshots()
                        self._send(
                            render_federated(snaps, missing_hosts=missing)
                            .encode(), "text/plain; version=0.0.4")
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": repr(e)}, 500)

            def log_message(self, *a):
                pass

        # raylint: allow(data-race) start() runs once from the owning process before the serve thread exists
        self._httpd = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), Handler)
        self.port = self._httpd.server_address[1]  # raylint: allow(data-race) start() runs once from the owning process before the serve thread exists
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dashboard-head")
        self._thread.start()
        self._start_autopilot()
        return self.port

    def stop(self):
        with self._autopilot_lock:
            ap, self.autopilot = self.autopilot, None
        if ap is not None:
            try:
                ap.stop()
            except Exception as e:  # noqa: BLE001
                logger.debug("autopilot stop failed: %s", e)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None  # raylint: allow(data-race) stop() runs after shutdown() has joined the serve loop; no reader remains
        try:
            self.pool.close_all()
        except Exception as e:
            logger.debug("connection pool close failed: %s", e)
        try:
            self.state.close()
        except Exception as e:
            logger.debug("state client close failed: %s", e)


def start_dashboard(state_addr: str, port: int = 0,
                    host: str = "127.0.0.1") -> DashboardHead:
    head = DashboardHead(state_addr, port=port, host=host)
    head.start()
    return head
