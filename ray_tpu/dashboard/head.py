"""Dashboard head: HTTP UI + JSON API over the state service.

Parity with ``dashboard/head.py:63`` / ``state_aggregator.py``: a single
HTTP server that renders cluster state. Everything is read live from the
C++ state service (tables + the ``node_stats`` reporter KV), so the head
can run in the driver, on the head node, or standalone against any
cluster address — it holds no state of its own.

Endpoints:
  /                 — self-contained HTML UI (polls the JSON API)
  /api/cluster      — nodes + reporter stats + resource totals
  /api/actors       — actor table
  /api/pgs          — placement groups
  /api/jobs         — job table
  /api/stats        — state-service counters
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ray_tpu.dashboard.agent import collect_node_stats

_PAGE = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa;color:#222}
h1{font-size:20px} h2{font-size:15px;margin-top:28px;color:#444}
table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
th,td{padding:6px 10px;border-bottom:1px solid #eee;text-align:left;font-size:13px}
th{background:#f0f0f3;font-weight:600}
.dead{color:#b00} .alive{color:#080}
#updated{color:#888;font-size:12px}
</style></head><body>
<h1>ray_tpu cluster <span id=updated></span></h1>
<h2>Nodes</h2><table id=nodes></table>
<h2>Actors</h2><table id=actors></table>
<h2>Placement groups</h2><table id=pgs></table>
<h2>Jobs</h2><table id=jobs></table>
<script>
// all dynamic values are escaped: actor/class/label names are
// user-controlled and must not inject HTML into the viewer's page
function esc(v){return String(v).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function row(cells, tag){tag=tag||'td';return '<tr>'+cells.map(c=>'<'+tag+'>'+c+'</'+tag+'>').join('')+'</tr>'}
function rowe(cells, tag){return row(cells.map(esc), tag)}
async function refresh(){
  const c = await (await fetch('/api/cluster')).json();
  let h = row(['node','address','state','CPU','TPU','cpu%','rss MB','arena','objects'],'th');
  for (const n of c.nodes){
    const s = n.stats||{}; const a = s.arena||{};
    h += row([esc(n.node_id.slice(0,8)), esc(n.address),
      '<span class="'+(n.alive?'alive':'dead')+'">'+(n.alive?'ALIVE':'DEAD')+'</span>',
      esc((n.available.CPU??0)+'/'+(n.total.CPU??0)),
      esc((n.available.TPU??'-')+'/'+(n.total.TPU??'-')),
      esc(s.cpu_percent??'-'), esc(s.rss_mb??'-'),
      esc(a.capacity_mb? a.used_mb+'/'+a.capacity_mb+' MB'+(a.owner?' (owner)':'') : '-'),
      esc((s.object_store||{}).num_objects??'-')]);
  }
  document.getElementById('nodes').innerHTML = h;
  const actors = await (await fetch('/api/actors')).json();
  let ah = row(['actor','class','state','node','restarts'],'th');
  for (const x of actors) ah += rowe([x.actor_id.slice(0,8), x.class_name, x.state, (x.node_id||'').slice(0,8), x.num_restarts??0]);
  document.getElementById('actors').innerHTML = ah;
  const pgs = await (await fetch('/api/pgs')).json();
  let ph = row(['pg','strategy','state','bundles'],'th');
  for (const p of pgs) ph += rowe([p.pg_id.slice(0,8), p.strategy, p.state, p.num_bundles]);
  document.getElementById('pgs').innerHTML = ph;
  const jobs = await (await fetch('/api/jobs')).json();
  let jh = row(['job','driver','state'],'th');
  for (const j of jobs) jh += rowe([j.job_id, j.driver_address, j.state]);
  document.getElementById('jobs').innerHTML = jh;
  document.getElementById('updated').textContent = 'updated '+new Date().toLocaleTimeString();
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class DashboardHead:
    """Serves the UI + API against one cluster's state service."""

    def __init__(self, state_addr: str, port: int = 0,
                 host: str = "127.0.0.1"):
        from ray_tpu._private.state_client import StateClient
        self.state = StateClient(state_addr)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._host, self._want_port = host, port
        self.port: Optional[int] = None

    # -- API payloads ----------------------------------------------------
    def _cluster(self) -> dict:
        stats = collect_node_stats(self.state)
        nodes = []
        for n in self.state.list_nodes():
            nid = n.node_id.hex()
            nodes.append({
                "node_id": nid,
                "address": n.address,
                "alive": n.alive,
                "is_head": n.is_head,
                "total": dict(n.total.amounts),
                "available": dict(n.available.amounts),
                "labels": dict(n.labels),
                "death_reason": n.death_reason,
                "stats": stats.get(nid),
            })
        return {"ts": time.time(), "nodes": nodes}

    def _actors(self) -> list:
        return [{
            "actor_id": a.actor_id.hex(),
            "class_name": a.class_name,
            "state": a.state,
            "node_id": a.node_id.hex() if a.node_id else "",
            "name": a.name,
            "num_restarts": a.restart_count,
        } for a in self.state.list_actors()]

    def _pgs(self) -> list:
        return [{
            "pg_id": p.pg_id.hex(),
            "strategy": p.strategy,
            "state": p.state,
            "num_bundles": len(p.bundles),
        } for p in self.state.list_pgs()]

    def _jobs(self) -> list:
        return [{
            "job_id": j.job_id.hex(),
            "driver_address": j.driver_address,
            "state": j.state,
        } for j in self.state.list_jobs()]

    # -- server ----------------------------------------------------------
    def start(self) -> int:
        import http.server
        head = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, payload, code: int = 200):
                self._send(json.dumps(payload, default=str).encode(),
                           "application/json", code)

            def do_GET(self):
                try:
                    if self.path in ("/", "/index.html"):
                        self._send(_PAGE.encode(), "text/html")
                    elif self.path == "/api/cluster":
                        self._json(head._cluster())
                    elif self.path == "/api/actors":
                        self._json(head._actors())
                    elif self.path == "/api/pgs":
                        self._json(head._pgs())
                    elif self.path == "/api/jobs":
                        self._json(head._jobs())
                    elif self.path == "/api/stats":
                        self._json(head.state.stats())
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": repr(e)}, 500)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dashboard-head")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        try:
            self.state.close()
        except Exception:
            pass


def start_dashboard(state_addr: str, port: int = 0,
                    host: str = "127.0.0.1") -> DashboardHead:
    head = DashboardHead(state_addr, port=port, host=host)
    head.start()
    return head
