"""ray_tpu.dashboard — cluster dashboard head + per-node reporter agent.

The TPU-native re-design of the reference's dashboard
(``dashboard/head.py:63``, ``dashboard/agent.py:51``): instead of an
aiohttp head process aggregating gRPC streams from per-node agents, the
head here is one stdlib HTTP server that reads everything from the C++
state service (node/actor/PG/job tables plus the ``node_stats`` KV
namespace), and the agent is a daemon thread inside each host daemon
sampling /proc and publishing one JSON blob per heartbeat-ish interval.
No external UI build: ``/`` serves a self-contained HTML page that polls
the JSON API.
"""

from ray_tpu.dashboard.agent import NodeReporterAgent  # noqa: F401
from ray_tpu.dashboard.head import DashboardHead, start_dashboard  # noqa: F401
