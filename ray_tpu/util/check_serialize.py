"""Serializability inspection.

Parity with ``python/ray/util/check_serialize.py``
(``inspect_serializability``): attempt cloudpickle, and on failure walk
closures/attributes to pinpoint the unserializable leaves instead of
surfacing one opaque error.
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

import cloudpickle


class FailureTuple:
    """One unserializable object found during inspection."""

    def __init__(self, obj: Any, name: str, parent: str):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.obj!r}, name={self.name}, " \
               f"parent={self.parent})"


def _serializable(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:  # raylint: allow(swallow) the whole point is try-pickle
        return False


def inspect_serializability(obj: Any, name: str = "object", depth: int = 3
                            ) -> Tuple[bool, Set[FailureTuple]]:
    """-> (is_serializable, failures). Failures name the deepest
    unserializable members found within ``depth`` levels."""
    failures: Set[FailureTuple] = set()
    _inspect(obj, name, "root", depth, failures)
    return (not failures, failures)


def _inspect(obj: Any, name: str, parent: str, depth: int,
             failures: Set[FailureTuple]) -> bool:
    if _serializable(obj):
        return True
    if depth <= 0:
        failures.add(FailureTuple(obj, name, parent))
        return False
    found_deeper = False
    # Closures of functions.
    if inspect.isfunction(obj) and obj.__closure__:
        for var, cell in zip(obj.__code__.co_freevars, obj.__closure__):
            try:
                contents = cell.cell_contents
            except ValueError:
                continue
            if not _serializable(contents):
                found_deeper = True
                _inspect(contents, var, name, depth - 1, failures)
    # Instance attributes.
    attrs = getattr(obj, "__dict__", None)
    if isinstance(attrs, dict):
        for k, v in attrs.items():
            if not _serializable(v):
                found_deeper = True
                _inspect(v, k, name, depth - 1, failures)
    # Container elements.
    if isinstance(obj, (list, tuple, set)):
        for i, v in enumerate(obj):
            if not _serializable(v):
                found_deeper = True
                _inspect(v, f"{name}[{i}]", name, depth - 1, failures)
    if isinstance(obj, dict):
        for k, v in obj.items():
            if not _serializable(v):
                found_deeper = True
                _inspect(v, f"{name}[{k!r}]", name, depth - 1, failures)
    if not found_deeper:
        failures.add(FailureTuple(obj, name, parent))
    return False
