"""Actor pool utility.

Capability parity with the reference's ``python/ray/util/actor_pool.py``
(``ActorPool``): a fixed set of actors shared by a stream of tasks, with
ordered and unordered result retrieval.  The implementation here is written
against ray_tpu futures (``ray_tpu.wait`` drives completion) rather than a
translation of the reference code.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    """Pool of actor handles load-balancing a stream of submitted tasks.

    Example:
        >>> pool = ActorPool([Worker.remote() for _ in range(4)])
        >>> results = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        if not self._idle_actors:
            raise ValueError("ActorPool requires at least one actor")
        # future -> actor that produced it
        self._future_to_actor = {}
        # ordered bookkeeping: index -> future (+ reverse), next index to
        # submit/return
        self._index_to_future = {}
        self._future_to_index = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable[[Any, V], Any], value: V) -> None:
        """Schedule ``fn(actor, value)`` on the next idle actor.

        If no actor is idle the submit is queued and dispatched when one
        frees up (inside ``get_next``/``get_next_unordered``).
        """
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._future_to_index[future] = self._next_task_index
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    def get_next(self, timeout: float | None = None) -> Any:
        """Return results in submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([future], timeout=timeout)
            if not ready:
                raise TimeoutError("Timed out waiting for result")
        # Return the actor to the pool before ray_tpu.get so a task that
        # raises doesn't leak the actor as busy and wedge pending submits.
        del self._index_to_future[self._next_return_index]
        del self._future_to_index[future]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(future))
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Return whichever queued result completes first."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        future = ready[0]
        # Drop it from the ordered index too.
        idx = self._future_to_index.pop(future, None)
        if idx is not None:
            del self._index_to_future[idx]
        self._return_actor(self._future_to_actor.pop(future))
        return ray_tpu.get(future)

    def _return_actor(self, actor) -> None:
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def map(self, fn: Callable[[Any, V], Any],
            values: Iterable[V]) -> Iterator[Any]:
        """Apply ``fn`` over ``values``, yielding results in order."""
        for value in values:
            self.submit(fn, value)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]) -> Iterator[Any]:
        """Apply ``fn`` over ``values``, yielding results as they finish."""
        for value in values:
            self.submit(fn, value)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        busy = set(self._future_to_actor.values())
        if actor in self._idle_actors or actor in busy:
            raise ValueError("Actor already belongs to this pool")
        self._return_actor(actor)

    def pop_idle(self) -> Any | None:
        """Remove and return an idle actor, or None if none are idle."""
        if self.has_free():
            return self._idle_actors.pop()
        return None
