"""User-defined and runtime metrics with Prometheus exposition.

Parity with ``python/ray/util/metrics.py`` (Counter :155, Histogram :220,
Gauge :295) and the export side of the reference's metrics agent
(``python/ray/_private/metrics_agent.py:63,197`` — OpenCensus aggregation
to a Prometheus endpoint). One in-process registry replaces the per-node
agent: the host-granular runtime has one process per host, so exposition
is a text endpoint on the driver process.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_HISTOGRAM_BOUNDARIES = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0)


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, "Metric"] = {}

    def register(self, metric: "Metric"):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered with a "
                    f"different type")
            self._metrics[metric.name] = metric

    def metrics(self) -> List["Metric"]:
        with self._lock:
            return list(self._metrics.values())

    def clear(self):
        with self._lock:
            self._metrics.clear()


_registry = _Registry()

# Extra sample sources: callables returning snapshot()-shaped family
# dicts, merged into every snapshot/exposition.  The perf plane
# (observability/perf.py) registers here so its lock-free histograms
# export without living inside the registry's Metric class hierarchy.
_sources_lock = threading.Lock()
_extra_sources: List = []  # raylint: guarded-by(_sources_lock)


def register_sample_source(fn) -> None:
    """Register a zero-arg callable returning a list of family dicts
    (``{"name","type","help","samples",...}``) to include in
    :func:`snapshot` and the Prometheus expositions."""
    with _sources_lock:
        if fn not in _extra_sources:
            _extra_sources.append(fn)


def _extra_families() -> List[dict]:
    with _sources_lock:
        sources = list(_extra_sources)
    out: List[dict] = []
    for fn in sources:
        try:
            out.extend(fn())
        except Exception:  # raylint: allow(swallow) one bad source must not kill the scrape
            pass
    return out


def _escape_label(value: str) -> str:
    """Prometheus label escaping: backslash, quote, newline — one bad
    value must not invalidate the whole scrape."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_tags(tags: Tuple[Tuple[str, str], ...]) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in tags)
    return "{" + inner + "}"


class Metric:
    """Base: named, described, tagged. Subclasses record values."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or any(c in name for c in " -"):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}  # raylint: guarded-by(self._lock)
        self._default_tags: Dict[str, str] = {}  # raylint: guarded-by(self._lock)
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        with self._lock:
            self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]
             ) -> Tuple[Tuple[str, str], ...]:
        with self._lock:
            merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self.tag_keys)
            if unknown:
                raise ValueError(
                    f"unknown tag keys {sorted(unknown)} for {self.name!r} "
                    f"(declared: {list(self.tag_keys)})")
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        with self._lock:
            return [(self.name, tags, v) for tags, v in self._values.items()]


class Counter(Metric):
    """Monotonic count (``metrics.py:155``)."""

    TYPE = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    """Point-in-time value (``metrics.py:295``)."""

    TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)  # outside the lock: _key re-acquires it
        with self._lock:
            self._values[key] = float(value)


class Histogram(Metric):
    """Bucketed distribution (``metrics.py:220``)."""

    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(sorted(boundaries
                                       or _DEFAULT_HISTOGRAM_BOUNDARIES))
        self._buckets: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}  # raylint: guarded-by(self._lock)
        self._sums: Dict[Tuple[Tuple[str, str], ...], float] = {}  # raylint: guarded-by(self._lock)
        self._counts: Dict[Tuple[Tuple[str, str], ...], int] = {}  # raylint: guarded-by(self._lock)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            buckets[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0) + 1

    def samples(self):
        out = []
        with self._lock:
            for key, buckets in self._buckets.items():
                cum = 0
                for b, n in zip(self.boundaries, buckets):
                    cum += n
                    out.append((f"{self.name}_bucket",
                                key + (("le", str(b)),), float(cum)))
                cum += buckets[-1]
                out.append((f"{self.name}_bucket",
                            key + (("le", "+Inf"),), float(cum)))
                out.append((f"{self.name}_sum", key, self._sums[key]))
                out.append((f"{self.name}_count", key,
                            float(self._counts[key])))
        return out


def generate_prometheus_text() -> str:
    """Prometheus text exposition format of every registered metric."""
    lines = []
    for m in _registry.metrics():
        if m.description:
            lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.TYPE}")
        for name, tags, value in m.samples():
            lines.append(f"{name}{_fmt_tags(tags)} {value}")
    for fam in _extra_families():
        if fam.get("help"):
            lines.append(f"# HELP {fam['name']} {fam['help']}")
        lines.append(f"# TYPE {fam['name']} {fam['type']}")
        for name, tags, value in fam["samples"]:
            lines.append(f"{name}{_fmt_tags(tuple(map(tuple, tags)))} {value}")
    return "\n".join(lines) + "\n"


def snapshot() -> List[dict]:
    """JSON-able dump of every registered metric — the per-host half of
    cluster metrics federation (shipped in NODE_DEBUG replies and merged
    by the dashboard head into one exposition)."""
    out = []
    for m in _registry.metrics():
        out.append({
            "name": m.name,
            "type": m.TYPE,
            "help": m.description,
            "samples": [[name, list(map(list, tags)), value]
                        for name, tags, value in m.samples()],
        })
    out.extend(_extra_families())
    return out


def render_federated(snapshots: Dict[str, List[dict]],
                     missing_hosts: Optional[List[dict]] = None) -> str:
    """Prometheus text for many hosts' :func:`snapshot` dumps, each
    sample labeled with its source ``node`` — the cluster-wide exposition
    endpoint (one scrape covers every host, the reference's per-node
    metrics agents rolled up by the dashboard). Hosts the head could not
    reach this scrape surface as ``federation_missing_hosts`` samples so
    alerting can distinguish "node quiet" from "node unscraped"."""
    lines = []
    typed = set()
    for node, families in snapshots.items():
        for fam in families:
            if fam["name"] not in typed:
                typed.add(fam["name"])
                if fam.get("help"):
                    lines.append(f"# HELP {fam['name']} {fam['help']}")
                lines.append(f"# TYPE {fam['name']} {fam['type']}")
            for name, tags, value in fam["samples"]:
                merged = (("node", node),) + tuple(
                    (k, v) for k, v in tags)
                lines.append(f"{name}{_fmt_tags(merged)} {value}")
    if missing_hosts:
        lines.append("# HELP federation_missing_hosts Hosts registered "
                     "alive but unreachable during this federated scrape")
        lines.append("# TYPE federation_missing_hosts gauge")
        for h in missing_hosts:
            tags = (("node", str(h.get("node_id", ""))[:8]),
                    ("address", str(h.get("address", ""))))
            lines.append(f"federation_missing_hosts{_fmt_tags(tags)} 1.0")
    return "\n".join(lines) + "\n"


_server = None


def start_metrics_server(port: int = 0) -> int:
    """Serve ``/metrics`` on a daemon thread; returns the bound port
    (the reference's Prometheus endpoint, ``metrics_agent.py:197``)."""
    global _server
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = generate_prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    _server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="metrics-server")
    t.start()
    return _server.server_address[1]


def stop_metrics_server():
    global _server
    if _server is not None:
        _server.shutdown()
        _server.server_close()  # release the listening socket now, not at GC
        _server = None
