"""Client side: stubs that proxy the public API over the socket.

Parity with the stub layer of Ray Client (``util/client/common.py``
``ClientObjectRef``/``ClientActorHandle``/``ClientRemoteFunc``). One
socket, MULTIPLEXED: every request carries a seq, a reader thread
matches responses, and the server dispatches each request on its own
worker — so a second in-flight call (e.g. a quick ``put`` while a long
``get`` blocks) no longer waits for the first to finish (the
reference's ``proxier.py`` stream multiplexing role)."""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.util.client.protocol import recv_msg, send_msg


class ClientObjectRef:
    def __init__(self, api: "ClientAPI", ref_id: str):
        self._api = api
        self.ref_id = ref_id

    def __repr__(self):
        return f"ClientObjectRef({self.ref_id[:8]})"

    def __reduce__(self):
        # On the wire a ref is just its server-side id; the server swaps
        # the marker for the real ObjectRef (args travel pickled).
        from ray_tpu.util.client.protocol import RefMarker
        return (RefMarker, (self.ref_id,))


class _ClientActorMethod:
    def __init__(self, api: "ClientAPI", actor_key: str, method: str):
        self._api = api
        self._actor_key = actor_key
        self._method = method

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        rid = self._api._call({
            "op": "actor_call", "actor_key": self._actor_key,
            "method": self._method,
            "args": args, "kwargs": kwargs})
        return ClientObjectRef(self._api, rid)


class ClientActorHandle:
    def __init__(self, api: "ClientAPI", actor_key: str):
        self._api = api
        self._actor_key = actor_key

    def __getattr__(self, name: str) -> _ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self._api, self._actor_key, name)


class ClientRemoteFunction:
    def __init__(self, api: "ClientAPI", fn_id: str,
                 options: Optional[dict] = None):
        self._api = api
        self._fn_id = fn_id
        self._options = options

    def options(self, **opts) -> "ClientRemoteFunction":
        merged = dict(self._options or {})
        merged.update(opts)
        return ClientRemoteFunction(self._api, self._fn_id, merged)

    def remote(self, *args, **kwargs):
        out = self._api._call({
            "op": "task", "fn_id": self._fn_id,
            "options": self._options,
            "args": args, "kwargs": kwargs})
        if isinstance(out, list):
            return [ClientObjectRef(self._api, r) for r in out]
        return ClientObjectRef(self._api, out)


class ClientActorClass:
    def __init__(self, api: "ClientAPI", cls_id: str,
                 options: Optional[dict] = None):
        self._api = api
        self._cls_id = cls_id
        self._options = options

    def options(self, **opts) -> "ClientActorClass":
        merged = dict(self._options or {})
        merged.update(opts)
        return ClientActorClass(self._api, self._cls_id, merged)

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        key = self._api._call({
            "op": "actor_create", "cls_id": self._cls_id,
            "options": self._options,
            "args": args, "kwargs": kwargs})
        return ClientActorHandle(self._api, key)


class ClientAPI:
    """The ``ray_tpu`` surface, proxied (init/get/put/wait/remote/...)."""

    def __init__(self, address: str, timeout: float = 30.0):
        host, _, port = address.partition(":")
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        try:
            self._sock.settimeout(None)
            self._send_lock = threading.Lock()
            self._pending_lock = threading.Lock()
            self._pending: Dict[int, list] = {}  # raylint: guarded-by(self._pending_lock)
            self._seq = 0
            self._closed: Optional[Exception] = None
            self._reader = threading.Thread(target=self._read_loop,
                                            daemon=True, name="client-reader")
            self._reader.start()
            assert self._call({"op": "ping"},
                              timeout=timeout)["initialized"], \
                "server head is not initialized"
        except Exception:
            # a failed handshake (wrong server, dead head) must close the fd
            try:
                self._sock.close()
            except OSError:
                pass
            raise

    def _read_loop(self):
        try:
            while True:
                resp = recv_msg(self._sock)
                if resp is None:
                    raise ConnectionError(
                        "client server closed the connection")
                with self._pending_lock:
                    slot = self._pending.pop(resp.get("seq"), None)
                if slot is not None:
                    slot[1] = resp
                    slot[0].set()
        except BaseException as e:  # noqa: BLE001 - teardown path
            with self._pending_lock:
                # raylint: allow(data-race) set under _pending_lock before slot events fire; post-wait readers see it via the event's happens-before edge
                self._closed = e if isinstance(e, Exception) else \
                    ConnectionError(str(e))
                pending, self._pending = dict(self._pending), {}
            for slot in pending.values():
                slot[0].set()

    def _call(self, req: dict, timeout: Optional[float] = None):
        slot = [threading.Event(), None]
        with self._pending_lock:
            if self._closed is not None:
                raise ConnectionError(
                    f"client connection closed: {self._closed}")
            self._seq += 1
            seq = self._seq
            self._pending[seq] = slot
        try:
            with self._send_lock:
                send_msg(self._sock, dict(req, seq=seq))
            if not slot[0].wait(timeout):
                raise TimeoutError(
                    f"no reply to {req.get('op')!r} within {timeout}s")
        finally:
            with self._pending_lock:
                self._pending.pop(seq, None)
        resp = slot[1]
        if resp is None:
            raise ConnectionError(
                f"client connection lost mid-call: {self._closed}")
        if "error" in resp:
            raise resp["error"]
        return resp["ok"]

    # -- API ----------------------------------------------------------------

    def remote(self, fn_or_class, **options):
        """Wrap a function or class for remote execution on the server."""
        if isinstance(fn_or_class, type):
            cls_id = self._call({"op": "register_class",
                                 "cls": fn_or_class})
            return ClientActorClass(self, cls_id, options or None)
        fn_id = self._call({"op": "register_function",
                            "function": fn_or_class})
        return ClientRemoteFunction(self, fn_id, options or None)

    def put(self, value: Any) -> ClientObjectRef:
        return ClientObjectRef(self, self._call({"op": "put",
                                                 "value": value}))

    def get(self, refs: Union[ClientObjectRef, Sequence[ClientObjectRef]],
            timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        values = self._call({"op": "get",
                             "refs": [r.ref_id for r in ref_list],
                             "timeout": timeout})
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *,
             num_returns: int = 1, timeout: Optional[float] = None):
        by_id: Dict[str, ClientObjectRef] = {r.ref_id: r for r in refs}
        ready, pending = self._call({
            "op": "wait", "refs": [r.ref_id for r in refs],
            "num_returns": num_returns, "timeout": timeout})
        return ([by_id[r] for r in ready], [by_id[r] for r in pending])

    def get_actor(self, name: str,
                  namespace: Optional[str] = None) -> ClientActorHandle:
        key = self._call({"op": "get_actor", "name": name,
                          "namespace": namespace})
        return ClientActorHandle(self, key)

    def kill(self, actor: ClientActorHandle, *, no_restart: bool = True):
        return self._call({"op": "kill", "actor_key": actor._actor_key,
                           "no_restart": no_restart})

    def release(self, refs: Sequence[ClientObjectRef]):
        """Drop the server-side pins for these refs."""
        self._call({"op": "release",
                    "refs": [r.ref_id for r in refs]})

    def cluster_resources(self) -> Dict[str, float]:
        return self._call({"op": "cluster_resources"})

    def disconnect(self):
        try:
            self._sock.close()
        except OSError:
            pass


def connect(address: str, timeout: float = 30.0) -> ClientAPI:
    """Connect to a ``ClientServer`` in a head process."""
    return ClientAPI(address, timeout=timeout)
