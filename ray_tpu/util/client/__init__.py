"""Thin-client mode: drive a remote head process over a socket.

Parity with Ray Client (``python/ray/util/client/``, design doc
``ARCHITECTURE.md``): the client holds stubs (``ClientObjectRef``,
``ClientActorHandle``); the server runs a real driver inside the head
process and owns every object/actor the client references. The
reference's gRPC + protobuf wire (``ray_client.proto``) is replaced by
length-prefixed cloudpickle frames over TCP — same topology, simpler
substrate (the control plane rides DCN either way).

Usage::

    # head process
    from ray_tpu.util.client.server import ClientServer
    server = ClientServer(port=0)          # after ray_tpu.init()

    # remote driver
    from ray_tpu.util import client
    api = client.connect(f"127.0.0.1:{server.port}")
    f = api.remote(lambda x: x + 1)
    assert api.get(f.remote(1)) == 2
"""

from ray_tpu.util.client.client import (ClientActorHandle, ClientAPI,
                                        ClientObjectRef, connect)

__all__ = ["connect", "ClientAPI", "ClientObjectRef", "ClientActorHandle"]
