"""Server side: executes client requests against the in-process runtime.

Parity with ``python/ray/util/client/server/server.py`` (the dataservicer
running a real driver) and ``proxier.py`` (N clients multiplexed onto one
head — here each connection gets a thread, all sharing the runtime).
Object and actor ownership lives here: the server pins every ObjectRef a
client has been handed until that client releases it or disconnects
(reference: server-side reference tracking in ``server.py``).
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import uuid
from typing import Any, Dict

from ray_tpu.util.client.protocol import recv_msg, send_msg

logger = logging.getLogger("ray_tpu")


class _ClientSession:
    """Per-connection state: the refs/actors this client holds."""

    def __init__(self):
        self.refs: Dict[str, Any] = {}       # ref id -> ObjectRef
        self.actors: Dict[str, Any] = {}     # actor key -> ActorHandle
        self.functions: Dict[str, Any] = {}  # fn id -> RemoteFunction
        self.classes: Dict[str, Any] = {}    # cls id -> ActorClass


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from ray_tpu.util.client.protocol import _RESTORE_RESOLVER
                session = _ClientSession()
                sock = self.request

                def resolve(ref_id: str):
                    try:
                        return session.refs[ref_id]
                    except KeyError:
                        raise ValueError(
                            f"client ref {ref_id[:8]} is unknown to this "
                            f"session (freed or from another session)")

                from concurrent.futures import ThreadPoolExecutor
                wlock = threading.Lock()
                pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="client-srv")

                def run_one(req, seq):
                    try:
                        payload = {"seq": seq,
                                   "ok": outer._dispatch(session, req)}
                    except BaseException as e:  # noqa: BLE001
                        payload = {"seq": seq, "error": e}
                    try:
                        with wlock:
                            send_msg(sock, payload)
                    except (ConnectionError, OSError):
                        pass
                    except BaseException as e:  # noqa: BLE001
                        # Unpicklable result/exception: the client must
                        # still get SOME reply or it blocks forever.
                        try:
                            with wlock:
                                send_msg(sock, {
                                    "seq": seq,
                                    "error": RuntimeError(
                                        "response serialization failed: "
                                        f"{type(e).__name__}: {e}")})
                        except BaseException:  # raylint: allow(swallow) socket dead: no channel left to report on
                            pass

                try:
                    while True:
                        # markers anywhere in the request swap for real
                        # refs DURING unpickling (protocol.RefMarker) —
                        # parsing stays on the reader thread so the
                        # resolver contextvar scopes correctly
                        token = _RESTORE_RESOLVER.set(resolve)
                        try:
                            req = recv_msg(sock)
                        finally:
                            _RESTORE_RESOLVER.reset(token)
                        if req is None:
                            break
                        # Each request dispatches on its own worker: a
                        # blocking get() must not serialize the client's
                        # other calls behind it.
                        pool.submit(run_one, req, req.get("seq"))
                except (ConnectionError, OSError):
                    pass
                finally:
                    pool.shutdown(wait=False)
                    # Disconnect releases everything the client held.
                    session.refs.clear()
                    session.actors.clear()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="client-server")
        self._thread.start()

    # -- op dispatch --------------------------------------------------------

    def _dispatch(self, session: _ClientSession, req: dict):
        import ray_tpu
        op = req["op"]
        if op == "ping":
            return {"initialized": ray_tpu.is_initialized()}
        if op == "put":
            ref = ray_tpu.put(req["value"])
            return self._track(session, [ref])[0]
        if op == "get":
            refs = [session.refs[r] for r in req["refs"]]
            return ray_tpu.get(refs, timeout=req.get("timeout"))
        if op == "wait":
            refs = [session.refs[r] for r in req["refs"]]
            by_id = {id(ref): rid for rid, ref in
                     zip(req["refs"], refs)}
            ready, pending = ray_tpu.wait(
                refs, num_returns=req["num_returns"],
                timeout=req.get("timeout"))
            return ([by_id[id(r)] for r in ready],
                    [by_id[id(r)] for r in pending])
        if op == "register_function":
            fn_id = uuid.uuid4().hex
            session.functions[fn_id] = ray_tpu.remote(req["function"]) \
                if not hasattr(req["function"], "remote") \
                else req["function"]
            return fn_id
        if op == "task":
            fn = session.functions[req["fn_id"]]
            if req.get("options"):
                fn = fn.options(**req["options"])
            out = fn.remote(*req["args"], **req["kwargs"])
            refs = out if isinstance(out, list) else [out]
            ids = self._track(session, refs)
            return ids if isinstance(out, list) else ids[0]
        if op == "register_class":
            cls_id = uuid.uuid4().hex
            session.classes[cls_id] = ray_tpu.remote(req["cls"])
            return cls_id
        if op == "actor_create":
            cls = session.classes[req["cls_id"]]
            if req.get("options"):
                cls = cls.options(**req["options"])
            handle = cls.remote(*req["args"], **req["kwargs"])
            actor_key = uuid.uuid4().hex
            session.actors[actor_key] = handle
            return actor_key
        if op == "actor_call":
            handle = session.actors[req["actor_key"]]
            ref = getattr(handle, req["method"]).remote(
                *req["args"], **req["kwargs"])
            return self._track(session, [ref])[0]
        if op == "get_actor":
            handle = ray_tpu.get_actor(req["name"],
                                       namespace=req.get("namespace"))
            actor_key = uuid.uuid4().hex
            session.actors[actor_key] = handle
            return actor_key
        if op == "kill":
            ray_tpu.kill(session.actors[req["actor_key"]],
                         no_restart=req.get("no_restart", True))
            return True
        if op == "release":
            for rid in req["refs"]:
                session.refs.pop(rid, None)
            return True
        if op == "cluster_resources":
            return ray_tpu.cluster_resources()
        raise ValueError(f"unknown op {op!r}")

    @staticmethod
    def _track(session: _ClientSession, refs) -> list:
        ids = []
        for ref in refs:
            rid = uuid.uuid4().hex
            session.refs[rid] = ref
            ids.append(rid)
        return ids

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
