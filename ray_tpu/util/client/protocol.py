"""Wire framing shared by client and server.

Length-prefixed cloudpickle frames (the ``ray_client.proto`` role). Every
request carries an ``op`` and gets exactly one response frame:
``{"ok": value}`` or ``{"error": exception}``.
"""

from __future__ import annotations

import contextvars
import socket
import struct

import cloudpickle

MAX_FRAME = 1 << 30

# Server-side: set to a ``ref_id -> ObjectRef`` resolver around request
# decoding, so markers are swapped for real refs DURING unpickling — at
# any depth of any object graph (lists, dict keys, dataclass attributes,
# ...), with no post-hoc container walk to keep complete.
_RESTORE_RESOLVER: "contextvars.ContextVar" = contextvars.ContextVar(
    "refmarker_resolver", default=None)


class RefMarker:
    """Wire stand-in for a ClientObjectRef inside pickled args: carries
    only the server-side ref id; the server swaps in the real ObjectRef
    (at reconstruction time when ``_RESTORE_RESOLVER`` is set)."""

    __slots__ = ("ref_id",)

    def __new__(cls, ref_id: str):
        resolver = _RESTORE_RESOLVER.get()
        if resolver is not None:
            return resolver(ref_id)  # replaces the marker in-place
        return super().__new__(cls)

    def __init__(self, ref_id: str):
        # skipped automatically when __new__ returned a non-RefMarker
        self.ref_id = ref_id


def send_msg(sock: socket.socket, payload) -> None:
    data = cloudpickle.dumps(payload)
    sock.sendall(struct.pack("!Q", len(data)) + data)


def recv_msg(sock: socket.socket):
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack("!Q", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    data = _recv_exact(sock, length)
    if data is None:
        return None
    return cloudpickle.loads(data)


def _recv_exact(sock: socket.socket, n: int):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)
