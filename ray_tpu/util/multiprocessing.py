"""``multiprocessing.Pool`` API on top of ray_tpu tasks.

Capability parity with ``python/ray/util/multiprocessing/pool.py``: a
drop-in ``Pool`` whose workers are cluster tasks instead of forked
processes, so the same code scales beyond one host.  Ordering, chunking,
``AsyncResult`` and the imap iterators follow the stdlib contract.

``processes`` bounds in-flight chunks for the synchronous paths
(``map``/``starmap``/``imap``/``imap_unordered``); the ``*_async`` variants
submit eagerly (they must return a handle immediately) and note so in
their docstrings.
"""

from __future__ import annotations
import logging

import itertools
import threading
# The stdlib contract raises multiprocessing.TimeoutError (a ProcessError
# subclass), so ported ``except multiprocessing.TimeoutError`` keeps working.
from multiprocessing import TimeoutError
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu

logger = logging.getLogger("ray_tpu")

__all__ = ["Pool", "AsyncResult", "TimeoutError"]


def _chunk(iterable: Iterable, size: int) -> Iterator[list]:
    it = iter(iterable)
    while True:
        block = list(itertools.islice(it, size))
        if not block:
            return
        yield block


def _run_chunk(fn, chunk, star, kwds):
    if star:
        return [fn(*args, **kwds) for args in chunk]
    return [fn(args, **kwds) for args in chunk]


class AsyncResult:
    """Handle for an in-flight map/apply; mirrors stdlib ``AsyncResult``.

    When a callback/error_callback is given, a daemon thread fires it as
    soon as the result completes (stdlib semantics), not lazily at
    ``get()`` time.
    """

    def __init__(self, refs: List[Any], single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._callback = callback  # raylint: guarded-by(self._lock)
        self._error_callback = error_callback  # raylint: guarded-by(self._lock)
        self._result = None
        self._done = False
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        if callback is not None or error_callback is not None:
            threading.Thread(
                target=self._collect, args=(None,), daemon=True).start()

    def _collect(self, timeout: Optional[float]) -> None:
        try:
            chunks = ray_tpu.get(self._refs, timeout=timeout)
        except ray_tpu.GetTimeoutError:
            raise TimeoutError("Result not ready within timeout")
        except Exception as e:  # task raised
            with self._lock:
                if self._done:
                    return
                self._error = e  # raylint: allow(data-race) published under self._lock before _done flips; get() reads only after observing _done
                self._done = True
                cb, self._error_callback = self._error_callback, None
            if cb is not None:
                cb(e)
            return
        flat = [item for chunk in chunks for item in chunk]
        with self._lock:
            if self._done:
                return
            self._result = flat[0] if self._single else flat  # raylint: allow(data-race) published under self._lock before _done flips; get() reads only after observing _done
            self._done = True
            cb, self._callback = self._callback, None
        if cb is not None:
            cb(self._result)

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self._done:
            self._collect(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("Result is not ready")
        if not self._done:
            self._collect(None)
        return self._error is None


class Pool:
    """Task-backed process pool."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be at least 1")
        self._processes = processes
        self._closed = False
        self._terminated = False
        self._outstanding: List[Any] = []  # refs cancellable by terminate()
        remote_args = dict(ray_remote_args or {})
        self._task = ray_tpu.remote(**remote_args)(_run_chunk) \
            if remote_args else ray_tpu.remote(_run_chunk)
        # Pool semantics run the initializer once per worker; with dynamic
        # tasks there is no persistent worker, so run it locally once for
        # side effects the caller expects (e.g. seeding globals).
        if initializer is not None:
            initializer(*initargs)

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunksize(self, n_items: int, chunksize: Optional[int]) -> int:
        if chunksize is not None:
            return max(1, chunksize)
        return max(1, n_items // (self._processes * 4) or 1)

    def _spawn(self, fn, chunk, star, kwds=None):
        ref = self._task.remote(fn, chunk, star, kwds or {})
        if len(self._outstanding) >= 4096:  # prune finished refs
            _, pending = ray_tpu.wait(
                self._outstanding, num_returns=len(self._outstanding),
                timeout=0)
            self._outstanding = pending
        self._outstanding.append(ref)
        return ref

    def _submit_all(self, fn, iterable, star, chunksize,
                    kwds=None) -> List[Any]:
        items = list(iterable)
        size = self._chunksize(len(items), chunksize)
        return [self._spawn(fn, chunk, star, kwds)
                for chunk in _chunk(items, size)]

    def _iter_chunks_bounded(self, fn, iterable, star, chunksize,
                             ordered: bool, lazy: bool = False) -> Iterator[Any]:
        """Yield chunk results keeping ≤ ``processes`` chunks in flight.

        ``lazy=True`` (imap) consumes the input iterable incrementally —
        infinite/streaming iterables work; chunksize then defaults to the
        stdlib's 1 instead of a len-derived heuristic.
        """
        if lazy:
            size = max(1, chunksize or 1)
            chunks = _chunk(iterable, size)
        else:
            items = list(iterable)
            size = self._chunksize(len(items), chunksize)
            chunks = _chunk(items, size)
        in_flight: List[Any] = []
        for chunk in itertools.islice(chunks, self._processes):
            in_flight.append(self._spawn(fn, chunk, star))
        while in_flight:
            if self._terminated:
                return
            if ordered:
                ref, in_flight = in_flight[0], in_flight[1:]
            else:
                ready, in_flight = ray_tpu.wait(in_flight, num_returns=1)
                ref = ready[0]
            nxt = next(chunks, None)
            if nxt is not None:
                in_flight.append(self._spawn(fn, nxt, star))
            yield from ray_tpu.get(ref)

    def apply(self, func: Callable, args: tuple = (), kwds: dict = None) -> Any:
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_running()
        refs = [self._spawn(func, [args], True, kwds or {})]
        return AsyncResult(refs, single=True, callback=callback,
                           error_callback=error_callback)

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        self._check_running()
        return list(self._iter_chunks_bounded(
            func, iterable, False, chunksize, ordered=True))

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        """Eager: submits every chunk immediately (cannot bound in-flight
        work and still return a handle without a pump thread)."""
        self._check_running()
        refs = self._submit_all(func, iterable, False, chunksize)
        return AsyncResult(refs, callback=callback,
                           error_callback=error_callback)

    def starmap(self, func: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_running()
        return list(self._iter_chunks_bounded(
            func, iterable, True, chunksize, ordered=True))

    def starmap_async(self, func: Callable, iterable: Iterable,
                      chunksize: Optional[int] = None, callback=None,
                      error_callback=None) -> AsyncResult:
        """Eager, like map_async."""
        self._check_running()
        refs = self._submit_all(func, iterable, True, chunksize)
        return AsyncResult(refs, callback=callback,
                           error_callback=error_callback)

    def imap(self, func: Callable, iterable: Iterable,
             chunksize: Optional[int] = None) -> Iterator[Any]:
        self._check_running()
        return self._iter_chunks_bounded(
            func, iterable, False, chunksize, ordered=True, lazy=True)

    def imap_unordered(self, func: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None) -> Iterator[Any]:
        self._check_running()
        return self._iter_chunks_bounded(
            func, iterable, False, chunksize, ordered=False, lazy=True)

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        """Close the pool and best-effort cancel outstanding chunk tasks."""
        self._closed = True
        self._terminated = True
        for ref in self._outstanding:
            try:
                ray_tpu.cancel(ref)
            except Exception as e:
                logger.debug("cancel of outstanding chunk failed: %s", e)
        self._outstanding.clear()

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
