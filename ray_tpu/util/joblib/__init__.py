"""joblib backend: scikit-learn parallelism on ray_tpu tasks.

Parity with ``python/ray/util/joblib/`` (``register_ray`` +
``ray_backend.py``): registers a joblib parallel backend that runs each
joblib batch as a ``ray_tpu`` task, so ``with joblib.parallel_backend
("ray_tpu"): ...`` fans sklearn work across the cluster.
"""

from __future__ import annotations

from joblib._parallel_backends import ParallelBackendBase
from joblib.parallel import register_parallel_backend


class _RayFuture:
    """Future-like: joblib retrieves via ``get(timeout)``. A watcher
    thread fires joblib's completion callback — joblib's retrieval loop
    polls job status and only consumes results after the callback flips
    it from PENDING (parallel.py BatchCompletionCallBack protocol)."""

    def __init__(self, ref, callback):
        import threading
        self._ref = ref
        self._event = threading.Event()
        self._result = None
        self._error = None

        def _watch():
            import ray_tpu
            try:
                self._result = ray_tpu.get(ref)
            except BaseException as e:  # noqa: BLE001
                self._error = e
            finally:
                self._event.set()
                if callback is not None:
                    callback()

        threading.Thread(target=_watch, daemon=True,
                         name="joblib-ray-watch").start()

    def get(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("joblib task timed out")
        if self._error is not None:
            raise self._error
        return self._result


class RayTpuBackend(ParallelBackendBase):
    """Each joblib batch executes as one cluster task."""

    supports_timeout = True
    supports_retrieve_callback = False
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **kwargs):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs):
        import ray_tpu
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None or n_jobs == 1:
            return 1
        if n_jobs == -1:
            if not ray_tpu.is_initialized():
                return 1
            return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        return n_jobs

    def submit(self, func, callback=None):
        import ray_tpu

        @ray_tpu.remote
        def _run_joblib_batch(f):
            return f()

        return _RayFuture(_run_joblib_batch.remote(func), callback)

    def terminate(self):
        pass

    def abort_everything(self, ensure_ready=True):
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)


def register_ray_tpu() -> None:
    register_parallel_backend("ray_tpu", RayTpuBackend)


register_ray = register_ray_tpu  # reference-compatible alias
