"""Scheduling strategies.

Parity with ``python/ray/util/scheduling_strategies.py``: the string
strategies ``"DEFAULT"`` (hybrid pack-then-spread) and ``"SPREAD"``, plus
placement-group and node-affinity strategies.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "object"
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str  # hex node id
    soft: bool = False


DEFAULT = "DEFAULT"
SPREAD = "SPREAD"
