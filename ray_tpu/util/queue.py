"""Distributed FIFO queue backed by an actor.

Capability parity with ``python/ray/util/queue.py`` in the reference: a
bounded/unbounded queue usable from any task or actor, with blocking and
non-blocking put/get and batch variants.

Design note: the backing actor's methods are all **non-blocking** — they
try the operation and return immediately.  Blocking semantics are
implemented caller-side by polling with backoff.  (The reference keeps
blocked waiters free by using an asyncio actor; in this runtime actor
methods occupy mailbox threads, so blocking inside the actor could exhaust
``max_concurrency`` and deadlock — caller-side waiting removes that class
of failure entirely.)
"""

from __future__ import annotations
import logging

import collections
import threading
import time
# Re-export the stdlib exceptions (as the reference does) so existing
# ``except queue.Empty`` handlers keep matching.
from queue import Empty, Full
from typing import Any, List, Optional

import ray_tpu

logger = logging.getLogger("ray_tpu")

_POLL_S = 0.005


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._items = collections.deque()
        self._lock = threading.Lock()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def try_put(self, item: Any) -> bool:
        with self._lock:
            if 0 < self.maxsize <= len(self._items):
                return False
            self._items.append(item)
            return True

    def try_put_batch(self, items: List[Any]) -> bool:
        with self._lock:
            if 0 < self.maxsize < len(self._items) + len(items):
                return False
            self._items.extend(items)
            return True

    def try_get(self) -> tuple:
        """Returns (ok, item)."""
        with self._lock:
            if not self._items:
                return False, None
            return True, self._items.popleft()

    def try_get_batch(self, num_items: int) -> tuple:
        with self._lock:
            if len(self._items) < num_items:
                return False, None
            return True, [self._items.popleft() for _ in range(num_items)]


class Queue:
    """A FIFO queue shared across tasks and actors.

    Args:
        maxsize: maximum number of items (0 = unbounded).
        actor_options: options forwarded to the backing actor (e.g. a
            ``name=`` to make the queue retrievable by name).
    """

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def _poll(self, op, timeout: Optional[float]) -> Any:
        """Run ``op`` until it reports success or the deadline passes.

        ``op`` returns (ok, value); timeout=0 means a single attempt.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, value = op()
            if ok:
                return True, value
            if deadline is not None and time.monotonic() >= deadline:
                return False, None
            time.sleep(_POLL_S)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            timeout = 0.0
        elif timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        # First attempt ships the item; while the queue stays full, poll
        # the cheap ``full()`` probe instead of re-serializing the payload
        # every tick, and only re-send once capacity appears.
        if ray_tpu.get(self.actor.try_put.remote(item)):
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                raise Full()
            self._poll(
                lambda: (not ray_tpu.get(self.actor.full.remote()), None),
                None if deadline is None
                else max(0.0, deadline - time.monotonic()))
            if ray_tpu.get(self.actor.try_put.remote(item)):
                return

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.try_put_batch.remote(list(items))):
            raise Full(f"Putting {len(items)} items would exceed maxsize "
                       f"{self.maxsize}")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            timeout = 0.0
        elif timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        ok, item = self._poll(
            lambda: ray_tpu.get(self.actor.try_get.remote()), timeout)
        if not ok:
            raise Empty()
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(self.actor.try_get_batch.remote(num_items))
        if not ok:
            raise Empty(f"Cannot get {num_items} items from the queue")
        return items

    def shutdown(self, force: bool = False) -> None:
        """Kill the backing actor.

        With ``force=False`` an empty method call is synchronously drained
        first, so operations already in the actor's mailbox complete before
        the kill; ``force=True`` kills immediately.
        """
        if self.actor is not None:
            if not force:
                try:
                    ray_tpu.get(self.actor.qsize.remote())
                except Exception as e:
                    logger.debug("queue drain probe failed: %s", e)
            ray_tpu.kill(self.actor)
        self.actor = None
