from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.placement_group import (  # noqa: F401
    placement_group, placement_group_table, remove_placement_group)

__all__ = ["ActorPool", "placement_group", "placement_group_table",
           "remove_placement_group"]
