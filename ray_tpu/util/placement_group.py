"""Placement groups.

Parity with ``python/ray/util/placement_group.py`` (``placement_group()``
:127, ``PlacementGroup`` :33, ``remove_placement_group`` :228,
``placement_group_table`` :267). Strategies PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD map to the bundle policies in
``ray_tpu/_private/scheduler.py`` (reference:
``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h:73-97``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.resources import ResourceSet

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID):
        self.id = pg_id

    def ready(self):
        """Returns an ObjectRef resolving to this PG once scheduled."""
        from ray_tpu.remote_function import remote
        from ray_tpu._private import worker as _worker
        rt = _worker.global_worker().runtime
        state = rt.placement_groups[self.id]

        @remote
        def _await_ready():
            state.ready.wait()
            if state.state != "CREATED":
                from ray_tpu.exceptions import PlacementGroupSchedulingError
                raise PlacementGroupSchedulingError(
                    f"placement group is {state.state}")
            return True
        return _await_ready.options(num_cpus=0).remote()

    def wait(self, timeout_seconds: float = 30) -> bool:
        from ray_tpu._private import worker as _worker
        rt = _worker.global_worker().runtime
        state = rt.placement_groups.get(self.id)
        if state is None:
            return False
        if not state.ready.wait(timeout_seconds):
            return False
        return state.state == "CREATED"

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        from ray_tpu._private import worker as _worker
        rt = _worker.global_worker().runtime
        state = rt.placement_groups[self.id]
        return [b.to_dict() for b in state.bundles]

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError("bundles must request positive resources")
    from ray_tpu._private import worker as _worker
    rt = _worker.global_worker().runtime
    state = rt.create_placement_group(
        [ResourceSet(b) for b in bundles], strategy, name)
    return PlacementGroup(state.pg_id)


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private import worker as _worker
    _worker.global_worker().runtime.remove_placement_group(pg.id)


def placement_group_table() -> Dict[str, dict]:
    from ray_tpu._private import worker as _worker
    rt = _worker.global_worker().runtime
    out = {}
    for pg_id, state in rt.placement_groups.items():
        out[pg_id.hex()] = {
            "placement_group_id": pg_id.hex(),
            "name": state.name,
            "strategy": state.strategy,
            "state": state.state,
            "bundles": {i: b.to_dict() for i, b in enumerate(state.bundles)},
            "bundle_nodes": ([n.hex() for n in state.bundle_nodes]
                             if state.bundle_nodes else None),
        }
    return out


def get_current_placement_group() -> Optional[PlacementGroup]:
    from ray_tpu._private.runtime import task_context
    pg = task_context.placement_group
    return pg
