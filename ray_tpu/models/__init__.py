from ray_tpu.models import resnet, transformer
from ray_tpu.models.transformer import TransformerConfig

__all__ = ["transformer", "resnet", "TransformerConfig"]
