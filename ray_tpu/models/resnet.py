"""ResNet (18/50) — the image-training benchmark model.

Functional flax-free implementation matching the reference's benchmark
workload (``release/air_tests/air_benchmarks/workloads/torch_benchmark.py``
trains torchvision resnet18; ``benchmarks.rst:163-174``). NHWC layout
(TPU-native; conv lowers onto the MXU), bfloat16 compute with float32
batch-norm statistics.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)   # resnet18
    num_classes: int = 1000
    width: int = 64
    bottleneck: bool = False
    dtype: Any = jnp.bfloat16


def resnet18(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig((2, 2, 2, 2), num_classes, bottleneck=False)


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig((3, 4, 6, 3), num_classes, bottleneck=True)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) \
        * math.sqrt(2.0 / fan_in)


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def init_params(key: jax.Array, cfg: ResNetConfig) -> Dict[str, Any]:
    keys = iter(jax.random.split(key, 256))
    params: Dict[str, Any] = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width),
                 "bn": _bn_init(cfg.width)},
        "stages": [],
    }
    cin = cfg.width
    expansion = 4 if cfg.bottleneck else 1
    for si, n_blocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** si)
        cout = cmid * expansion
        stage: List[Dict[str, Any]] = []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            blk: Dict[str, Any] = {}
            if cfg.bottleneck:
                blk["conv1"] = _conv_init(next(keys), 1, 1, cin, cmid)
                blk["bn1"] = _bn_init(cmid)
                blk["conv2"] = _conv_init(next(keys), 3, 3, cmid, cmid)
                blk["bn2"] = _bn_init(cmid)
                blk["conv3"] = _conv_init(next(keys), 1, 1, cmid, cout)
                blk["bn3"] = _bn_init(cout)
            else:
                blk["conv1"] = _conv_init(next(keys), 3, 3, cin, cmid)
                blk["bn1"] = _bn_init(cmid)
                blk["conv2"] = _conv_init(next(keys), 3, 3, cmid, cout)
                blk["bn2"] = _bn_init(cout)
            if stride != 1 or cin != cout:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["proj_bn"] = _bn_init(cout)
            stage.append(blk)
            cin = cout
        params["stages"].append(stage)
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes),
                               jnp.float32) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return jax.lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype),
        window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p):
    """Per-batch normalization statistics (training mode)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(xf, axis=(0, 1, 2), keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _basic_block(x, blk, stride, dtype):
    y = jax.nn.relu(_bn(_conv(x, blk["conv1"], stride, dtype),
                        blk["bn1"]))
    y = _bn(_conv(y, blk["conv2"], 1, dtype), blk["bn2"])
    sc = x
    if "proj" in blk:
        sc = _bn(_conv(x, blk["proj"], stride, dtype), blk["proj_bn"])
    return jax.nn.relu(y + sc)


def _bottleneck_block(x, blk, stride, dtype):
    y = jax.nn.relu(_bn(_conv(x, blk["conv1"], 1, dtype), blk["bn1"]))
    y = jax.nn.relu(_bn(_conv(y, blk["conv2"], stride, dtype),
                        blk["bn2"]))
    y = _bn(_conv(y, blk["conv3"], 1, dtype), blk["bn3"])
    sc = x
    if "proj" in blk:
        sc = _bn(_conv(x, blk["proj"], stride, dtype), blk["proj_bn"])
    return jax.nn.relu(y + sc)


def apply(params: Dict[str, Any], images: jax.Array,
          cfg: ResNetConfig) -> jax.Array:
    """images: [B, H, W, 3] -> logits [B, num_classes] (float32)."""
    dtype = cfg.dtype
    x = _conv(images, params["stem"]["conv"], 2, dtype)
    x = jax.nn.relu(_bn(x, params["stem"]["bn"]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    block = _bottleneck_block if cfg.bottleneck else _basic_block
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = block(x, blk, stride, dtype)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ params["head"]["w"] + params["head"]["b"]


def loss_fn(params, images, labels, cfg: ResNetConfig) -> jax.Array:
    logits = apply(params, images, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
