"""Flagship model: decoder-only transformer, TPU-first.

Design points (vs the reference, which delegates all modeling to torch):
- Pure-functional params pytree with a parallel *logical axes* pytree, so the
  whole model shards with one ``ShardingRules`` table (DP/FSDP/TP/SP/PP are
  config edits, not code changes).
- bfloat16 activations/params with float32 RMSNorm/softmax accumulation —
  the MXU-native dtype recipe.
- Attention runs the Pallas flash kernel on TPU (``ray_tpu.ops``) or ring
  attention when the mesh has a nontrivial ``seq`` axis (long-context path).
- ``jax.checkpoint`` (remat) per block trades FLOPs for HBM.
- RoPE positions, SwiGLU MLP, RMSNorm: the standard modern decoder recipe.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.ops import flash_attention
from ray_tpu.parallel.sequence import ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: Optional[int] = None     # GQA; defaults to n_heads
    d_ff: Optional[int] = None           # defaults to 4 * d_model (SwiGLU 8/3)
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash: bool = True
    rope_theta: float = 10000.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree (float32 master copy)."""
    k_embed, k_head, k_layers = jax.random.split(key, 3)
    d, h, kvh, hd, f = (cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim,
                        cfg.ff_dim)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)

    def layer(k):
        ks = jax.random.split(k, 7)
        return {
            "attn": {
                "wq": dense(ks[0], (d, h, hd), d),
                "wk": dense(ks[1], (d, kvh, hd), d),
                "wv": dense(ks[2], (d, kvh, hd), d),
                "wo": dense(ks[3], (h, hd, d), h * hd),
            },
            "mlp": {
                "wi": dense(ks[4], (d, f), d),       # gate
                "wg": dense(ks[5], (d, f), d),       # up
                "wo": dense(ks[6], (f, d), f),
            },
            "ln1": jnp.ones((d,), jnp.float32),
            "ln2": jnp.ones((d,), jnp.float32),
        }

    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    return {
        "embed": jax.random.normal(k_embed, (cfg.vocab_size, d),
                                   jnp.float32) * 0.02,
        "blocks": jax.vmap(layer)(layer_keys),      # stacked: [L, ...]
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": dense(k_head, (d, cfg.vocab_size), d),
    }


def logical_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical-axis pytree mirroring ``init_params`` output (leaves = tuples
    of logical names consumed by ``ShardingRules``). The leading "layers" dim
    of the stacked blocks maps to the pipeline axis when pipe > 1."""
    blk = {
        "attn": {
            "wq": ("layers", "embed", "heads", "kv"),
            "wk": ("layers", "embed", "heads", "kv"),
            "wv": ("layers", "embed", "heads", "kv"),
            "wo": ("layers", "heads", "kv", "embed"),
        },
        "mlp": {
            "wi": ("layers", "embed", "mlp"),
            "wg": ("layers", "embed", "mlp"),
            "wo": ("layers", "mlp", "embed"),
        },
        "ln1": ("layers", None),
        "ln2": ("layers", None),
    }
    return {
        "embed": ("vocab", "embed"),
        "blocks": blk,
        "ln_f": (None,),
        "lm_head": ("embed", "vocab"),
    }


def _rmsnorm(x: jax.Array, w: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w.astype(x.dtype)


def _rope(x: jax.Array, theta: float, positions: jax.Array) -> jax.Array:
    """x: [B, L, H, D]; rotate pairs along D."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (math.log(theta) / half))
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # B L 1 half
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def _attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh]):
    if mesh is not None and "seq" in mesh.axis_names and mesh.shape["seq"] > 1:
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.use_flash:
        return flash_attention(q, k, v, causal=True)
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(D)
    L, Lk = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((L, Lk), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block(params, x, positions, cfg: TransformerConfig, mesh):
    B, L, d = x.shape
    h = _rmsnorm(x, params["ln1"])
    q = jnp.einsum("bld,dhk->blhk", h, params["attn"]["wq"].astype(x.dtype))
    k = jnp.einsum("bld,dhk->blhk", h, params["attn"]["wk"].astype(x.dtype))
    v = jnp.einsum("bld,dhk->blhk", h, params["attn"]["wv"].astype(x.dtype))
    q = _rope(q, cfg.rope_theta, positions)
    k = _rope(k, cfg.rope_theta, positions)
    if cfg.kv_heads != cfg.n_heads:  # GQA: repeat kv heads
        rep = cfg.n_heads // cfg.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    attn = _attention(q, k, v, cfg, mesh)
    x = x + jnp.einsum("blhk,hkd->bld", attn,
                       params["attn"]["wo"].astype(x.dtype))
    h = _rmsnorm(x, params["ln2"])
    gate = jnp.einsum("bld,df->blf", h, params["mlp"]["wi"].astype(x.dtype))
    up = jnp.einsum("bld,df->blf", h, params["mlp"]["wg"].astype(x.dtype))
    ff = jax.nn.silu(gate) * up
    x = x + jnp.einsum("blf,fd->bld", ff, params["mlp"]["wo"].astype(x.dtype))
    return x


def backbone(params: Dict[str, Any], tokens: jax.Array,
             cfg: TransformerConfig,
             mesh: Optional[Mesh] = None) -> jax.Array:
    """Embedding + all transformer blocks; returns pre-final-norm states."""
    B, L = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(L)[None], (B, L))

    block_fn = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)

    def scan_body(x, layer_params):
        return block_fn(layer_params, x, positions), None

    # One scan over the stacked layer params: compiles a single block body
    # (fast compiles at depth) and keeps the layer dim shardable for PP.
    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return x


def head(params: Dict[str, Any], x: jax.Array,
         cfg: TransformerConfig) -> jax.Array:
    """Final norm + lm-head projection -> float32 logits. The single logits
    path shared by inference (``apply``) and training (``head_and_loss``)."""
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("bld,dv->blv", x,
                        params["lm_head"].astype(cfg.dtype))
    return logits.astype(jnp.float32)


def apply(params: Dict[str, Any], tokens: jax.Array,
          cfg: TransformerConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens: [B, L] int32 -> logits [B, L, vocab] (float32)."""
    x = backbone(params, tokens, cfg, mesh)
    return head(params, x, cfg)


def head_and_loss(params, x: jax.Array, targets: jax.Array,
                  cfg: TransformerConfig) -> jax.Array:
    """Final norm + lm head + next-token cross entropy, shared by the scan
    path (``loss_fn``) and the pipeline-parallel path (train.step)."""
    logits = head(params, x, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def loss_fn(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None) -> jax.Array:
    """Next-token cross entropy (tokens serve as their own labels)."""
    x = backbone(params, tokens[:, :-1], cfg, mesh)
    return head_and_loss(params, x, tokens[:, 1:], cfg)


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
