"""ctypes binding for the C++ shared-memory object store.

The Python face of ``object_store.cc`` (plasma client role, reference
``src/ray/object_manager/plasma/client.h``): put/get of immutable byte
payloads in the mmap arena, zero-copy reads via memoryview, LRU eviction
candidates for the spilling path.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

from ray_tpu._native.build import load_native_library


class NativeObjectStore:
    """Thin, thread-safe wrapper; raises ``RuntimeError`` if the native
    library cannot be built (callers should gate on ``available()``)."""

    @staticmethod
    def available() -> bool:
        return load_native_library("object_store") is not None

    def __init__(self, capacity_bytes: int):
        lib = load_native_library("object_store")
        if lib is None:
            raise RuntimeError("native object store unavailable")
        self._lib = lib
        lib.nps_create.restype = ctypes.c_void_p
        lib.nps_create.argtypes = [ctypes.c_uint64]
        lib.nps_destroy.argtypes = [ctypes.c_void_p]
        lib.nps_create_object.restype = ctypes.c_int
        lib.nps_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.nps_seal.restype = ctypes.c_int
        lib.nps_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.nps_get.restype = ctypes.c_int
        lib.nps_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.nps_unpin.restype = ctypes.c_int
        lib.nps_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.nps_delete.restype = ctypes.c_int
        lib.nps_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.nps_contains.restype = ctypes.c_int
        lib.nps_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.nps_evict_candidates.restype = ctypes.c_uint64
        lib.nps_evict_candidates.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.nps_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        self._handle = lib.nps_create(capacity_bytes)
        if not self._handle:
            raise RuntimeError("failed to create native store arena")
        self.capacity = capacity_bytes

    @staticmethod
    def _key(object_id: bytes) -> bytes:
        if len(object_id) > 16:
            raise ValueError("object id must be <= 16 bytes")
        return object_id.ljust(16, b"\0")

    def put(self, object_id: bytes, data: bytes) -> bool:
        """Create+write+seal. False if the id exists; raises MemoryError
        when the arena is full (caller evicts/spills then retries)."""
        key = self._key(object_id)
        out = ctypes.POINTER(ctypes.c_uint8)()
        rc = self._lib.nps_create_object(
            self._handle, key, len(data), ctypes.byref(out))
        if rc == -1:
            return False
        if rc == -2:
            raise MemoryError(
                f"native store full ({self.capacity} bytes); evict first")
        if data:
            ctypes.memmove(out, data, len(data))
        self._lib.nps_seal(self._handle, key)
        return True

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy read. The object is pinned until ``release``."""
        key = self._key(object_id)
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        rc = self._lib.nps_get(self._handle, key, ctypes.byref(ptr),
                               ctypes.byref(size), 1)
        if rc != 0:
            return None
        if size.value == 0:
            self._lib.nps_unpin(self._handle, key)
            return memoryview(b"")
        array = (ctypes.c_uint8 * size.value).from_address(
            ctypes.addressof(ptr.contents))
        return memoryview(array).cast("B")

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        """Copying read that immediately unpins."""
        view = self.get(object_id)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            self.release(object_id)

    def release(self, object_id: bytes) -> None:
        self._lib.nps_unpin(self._handle, self._key(object_id))

    def delete(self, object_id: bytes) -> bool:
        return self._lib.nps_delete(self._handle,
                                    self._key(object_id)) == 0

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.nps_contains(self._handle,
                                           self._key(object_id)))

    def evict_candidates(self, nbytes: int,
                         max_candidates: int = 1024) -> List[bytes]:
        """LRU (sealed, unpinned) ids whose eviction frees >= nbytes."""
        buf = ctypes.create_string_buffer(16 * max_candidates)
        n = self._lib.nps_evict_candidates(self._handle, nbytes, buf,
                                           max_candidates)
        return [buf.raw[i * 16:(i + 1) * 16] for i in range(n)]

    def stats(self) -> Tuple[int, int, int]:
        """-> (used_bytes, capacity_bytes, num_objects)."""
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        count = ctypes.c_uint64()
        self._lib.nps_stats(self._handle, ctypes.byref(used),
                            ctypes.byref(cap), ctypes.byref(count))
        return used.value, cap.value, count.value

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.nps_destroy(handle)
            self._handle = None
