"""ctypes binding for the C++ shared-memory object store.

The Python face of ``object_store.cc`` (plasma client role, reference
``src/ray/object_manager/plasma/client.h``): put/get of immutable byte
payloads in the mmap arena, zero-copy reads via memoryview, LRU eviction
candidates for the spilling path.

``NativeObjectStore`` owns an arena in-process; ``NativeStoreClient``
joins another process's served arena over its Unix socket (fd-passing) —
both expose the same op surface through ``_ArenaOps``, parameterized only
by the C symbol family (``nps_*`` vs ``npc_*``).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

from ray_tpu._native.build import load_native_library

_PTR = ctypes.POINTER(ctypes.c_uint8)
_U64P = ctypes.POINTER(ctypes.c_uint64)


def _bind_ops(lib: ctypes.CDLL, prefix: str) -> dict:
    """Bind the shared op family ``<prefix>_{create_object,seal,...}``
    once per library (argtypes are idempotent to re-set)."""
    ops = {}
    f = ops["create"] = getattr(lib, f"{prefix}_create_object")
    f.restype = ctypes.c_int
    f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                  ctypes.POINTER(_PTR)]
    f = ops["seal"] = getattr(lib, f"{prefix}_seal")
    f.restype = ctypes.c_int
    f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    f = ops["get"] = getattr(lib, f"{prefix}_get")
    f.restype = ctypes.c_int
    f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(_PTR),
                  _U64P, ctypes.c_int]
    for name in ("unpin", "delete", "contains"):
        f = ops[name] = getattr(lib, f"{prefix}_{name}")
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    f = ops["stats"] = getattr(lib, f"{prefix}_stats")
    f.argtypes = [ctypes.c_void_p, _U64P, _U64P, _U64P]
    return ops


class _ArenaOps:
    """Shared op surface over a store handle (owner or client).

    Every op checks the handle first: pin-release finalizers (zero-copy
    reads) can fire at interpreter teardown AFTER the client detached —
    calling into C with a dead handle would segfault."""

    _lib: ctypes.CDLL
    _handle: int
    _ops: dict
    capacity: int

    def _h(self):
        h = getattr(self, "_handle", None)
        if not h:
            raise RuntimeError("arena handle closed")
        return h

    @staticmethod
    def _key(object_id: bytes) -> bytes:
        if len(object_id) > 16:
            raise ValueError("object id must be <= 16 bytes")
        return object_id.ljust(16, b"\0")

    def create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        """Reserve ``size`` bytes; returns a writable view into the arena
        (write payload parts directly — zero intermediate copy), or None
        if the id already exists. ``seal`` when done. Raises MemoryError
        when the arena is full."""
        key = self._key(object_id)
        out = _PTR()
        rc = self._ops["create"](self._h(), key, size,
                                 ctypes.byref(out))
        if rc == -1:
            return None
        if rc == -2:
            raise MemoryError(
                f"arena full ({self.capacity} bytes); evict first")
        if rc != 0:
            raise RuntimeError(f"arena create failed rc={rc}")
        if size == 0:
            return memoryview(b"")
        array = (ctypes.c_uint8 * size).from_address(
            ctypes.addressof(out.contents))
        return memoryview(array).cast("B")

    def seal(self, object_id: bytes) -> None:
        self._ops["seal"](self._h(), self._key(object_id))

    def put(self, object_id: bytes, data: bytes) -> bool:
        """Create+write+seal. False if the id exists; raises MemoryError
        when the arena is full (caller evicts/spills then retries)."""
        view = self.create(object_id, len(data))
        if view is None:
            return False
        if data:
            view[:] = data
        self.seal(object_id)
        return True

    def put_pieces(self, object_id: bytes, pieces,
                   total: int) -> bool:
        """Create + scatter-write + seal: land an already-fragmented
        payload (pickle-5 out-of-band buffers plus framing) in the arena
        WITHOUT assembling it contiguously first — the only copy is the
        one into the arena pages. ``pieces`` must cover exactly
        ``total`` bytes in order. False if the id exists; raises
        MemoryError when the arena is full (caller evicts then retries)."""
        view = self.create(object_id, total)
        if view is None:
            return False
        pos = 0
        for p in pieces:
            mv = memoryview(p).cast("B")
            n = len(mv)
            if n:
                view[pos:pos + n] = mv
            pos += n
        self.seal(object_id)
        return True

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy read. The object is pinned until ``release``."""
        key = self._key(object_id)
        ptr = _PTR()
        size = ctypes.c_uint64()
        rc = self._ops["get"](self._h(), key, ctypes.byref(ptr),
                              ctypes.byref(size), 1)
        if rc != 0:
            return None
        if size.value == 0:
            self._ops["unpin"](self._h(), key)
            return memoryview(b"")
        array = (ctypes.c_uint8 * size.value).from_address(
            ctypes.addressof(ptr.contents))
        return memoryview(array).cast("B")

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        """Copying read that immediately unpins."""
        view = self.get(object_id)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            self.release(object_id)

    def release(self, object_id: bytes) -> None:
        try:
            self._ops["unpin"](self._h(), self._key(object_id))
        except RuntimeError:
            pass  # closed/detached: the pin died with the connection

    def delete(self, object_id: bytes) -> bool:
        return self._ops["delete"](self._h(),
                                   self._key(object_id)) == 0

    def contains(self, object_id: bytes) -> bool:
        return self._ops["contains"](self._h(),
                                     self._key(object_id)) == 1

    def stats(self) -> Tuple[int, int, int]:
        """-> (used_bytes, capacity_bytes, num_objects)."""
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        count = ctypes.c_uint64()
        self._ops["stats"](self._h(), ctypes.byref(used),
                           ctypes.byref(cap), ctypes.byref(count))
        return used.value, cap.value or self.capacity, count.value


class NativeObjectStore(_ArenaOps):
    """Arena owner; raises ``RuntimeError`` if the native library cannot
    be built (callers should gate on ``available()``)."""

    @staticmethod
    def available() -> bool:
        return load_native_library("object_store") is not None

    def __init__(self, capacity_bytes: int):
        lib = load_native_library("object_store")
        if lib is None:
            raise RuntimeError("native object store unavailable")
        self._lib = lib
        self._ops = _bind_ops(lib, "nps")
        lib.nps_create.restype = ctypes.c_void_p
        lib.nps_create.argtypes = [ctypes.c_uint64]
        lib.nps_destroy.argtypes = [ctypes.c_void_p]
        lib.nps_serve.restype = ctypes.c_int
        lib.nps_serve.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.nps_evict_candidates.restype = ctypes.c_uint64
        lib.nps_evict_candidates.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        self._handle = lib.nps_create(capacity_bytes)
        if not self._handle:
            raise RuntimeError("failed to create native store arena")
        self.capacity = capacity_bytes

    def evict_candidates(self, nbytes: int,
                         max_candidates: int = 1024) -> List[bytes]:
        """LRU (sealed, unpinned) ids whose eviction frees >= nbytes."""
        buf = ctypes.create_string_buffer(16 * max_candidates)
        n = self._lib.nps_evict_candidates(self._handle, nbytes, buf,
                                           max_candidates)
        return [buf.raw[i * 16:(i + 1) * 16] for i in range(n)]

    def serve(self, path: str) -> bool:
        """Serve this arena over a Unix domain socket: same-host peer
        processes connect with ``NativeStoreClient`` and map the SAME
        memfd pages (fd passed via SCM_RIGHTS) — a shared-memory read, not
        a TCP round-trip. Idempotent per store; refuses a store whose
        mapping fell back to private (nothing to share)."""
        return self._lib.nps_serve(self._handle, path.encode()) == 0

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.nps_destroy(handle)
            self._handle = None


class NativeStoreClient(_ArenaOps):
    """Same-host client of a served arena: identical surface to
    ``NativeObjectStore`` but reads/writes the OWNER's pages through the
    passed memfd (plasma client role, ``plasma/client.cc``)."""

    def __init__(self, socket_path: str):
        lib = load_native_library("object_store")
        if lib is None:
            raise RuntimeError("native object store unavailable")
        self._lib = lib
        self._ops = _bind_ops(lib, "npc")
        lib.npc_connect.restype = ctypes.c_void_p
        lib.npc_connect.argtypes = [ctypes.c_char_p]
        lib.npc_close.argtypes = [ctypes.c_void_p]
        lib.npc_capacity.restype = ctypes.c_uint64
        lib.npc_capacity.argtypes = [ctypes.c_void_p]
        lib.npc_detach.argtypes = [ctypes.c_void_p]
        self._handle = lib.npc_connect(socket_path.encode())
        if not self._handle:
            raise RuntimeError(f"cannot connect to arena at {socket_path}")
        self.capacity = lib.npc_capacity(self._handle)

    def close(self, unmap: bool = True) -> None:
        """``unmap=False`` keeps the arena mapping alive: zero-copy values
        already handed out reference those pages, and unmapping under them
        would turn a later read into a SIGSEGV. Use it on runtime shutdown;
        plain close() only when no decoded values can be outstanding."""
        handle = getattr(self, "_handle", None)
        if handle:
            if unmap:
                self._lib.npc_close(handle)
            else:
                self._lib.npc_detach(handle)
            self._handle = None

    def __del__(self):
        # GC cannot know whether decoded views are still alive — never
        # unmap implicitly
        self.close(unmap=False)
