"""Native (C++) runtime components, bound via ctypes.

The reference implements its runtime kernel in C++ (SURVEY §2.1); the
pieces here are the TPU-build equivalents that benefit from native code in
a host-granular runtime: the shared-memory object store arena
(``object_store.cc`` — plasma's role) built lazily with the system g++ and
cached next to the source.
"""

from ray_tpu._native.build import load_native_library  # noqa: F401
from ray_tpu._native.store import (NativeObjectStore,  # noqa: F401
                                   NativeStoreClient)
