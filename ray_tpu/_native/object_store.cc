// Native shared-memory object store: the plasma equivalent
// (reference: src/ray/object_manager/plasma/store.h, object_lifecycle_manager.h,
// plasma_allocator.h, eviction_policy.h), redesigned for the host-granular
// TPU runtime:
//
// - One mmap'd arena per host backed by memfd (sealed host-object bytes).
//   The arena is MAP_SHARED so future helper processes can map the same fd;
//   in the single-owner-process runtime, workers are threads and read the
//   buffers zero-copy through pointers handed across the C ABI.
// - Boundary-coalescing free-list allocator (dlmalloc.cc's role, simplified:
//   first-fit over an ordered free map with neighbor coalescing on free).
// - LRU eviction over sealed, unpinned objects (eviction_policy.h LRUCache):
//   the caller asks for candidates, spills them (local_object_manager.h:99
//   SpillObjects is the Python side), then deletes.
// - create -> write -> seal lifecycle with get() blocking handled in Python
//   (the store itself is non-blocking; CreateRequestQueue backpressure is
//   expressed as the -NOSPACE error code the caller turns into spilling).
//
// Host-sharing (this round): the arena owner can serve the store over a
// Unix domain socket (``nps_serve``). On connect the memfd is passed via
// SCM_RIGHTS and the client (``npc_*``) maps the SAME pages — a same-host
// get is a pointer into shared memory, not a TCP round-trip (reference:
// plasma's store socket + MaybeMmap fd passing, plasma/client.cc).
// Per-connection pin counts are rolled back on disconnect so a crashed
// client cannot pin objects forever.
//
// C ABI only — bound from Python via ctypes (no pybind11 in the image).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <thread>
#include <unordered_map>
#include <vector>

#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#ifdef __linux__
#include <sys/syscall.h>
#endif

namespace {

struct IdKey {
  uint8_t bytes[16];
  bool operator==(const IdKey& o) const {
    return std::memcmp(bytes, o.bytes, 16) == 0;
  }
};

struct IdHash {
  size_t operator()(const IdKey& k) const {
    uint64_t h;
    std::memcpy(&h, k.bytes, 8);
    uint64_t l;
    std::memcpy(&l, k.bytes + 8, 8);
    return static_cast<size_t>(h ^ (l * 0x9e3779b97f4a7c15ULL));
  }
};

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;
  int64_t pin_count = 0;
  uint64_t lru_tick = 0;
  bool sealed = false;
};

class Store {
 public:
  explicit Store(uint64_t capacity) : capacity_(capacity) {
#ifdef __linux__
    fd_ = static_cast<int>(syscall(SYS_memfd_create, "ray_tpu_plasma", 0));
#else
    fd_ = -1;
#endif
    if (fd_ >= 0 && ftruncate(fd_, static_cast<off_t>(capacity)) == 0) {
      base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                         PROT_READ | PROT_WRITE, MAP_SHARED,
                                         fd_, 0));
      if (base_ != MAP_FAILED) shared_backed_ = true;
    }
    if (base_ == MAP_FAILED || base_ == nullptr) {
      // Fallback: anonymous private mapping (no cross-process sharing).
      base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                         PROT_READ | PROT_WRITE,
                                         MAP_PRIVATE | MAP_ANONYMOUS, -1, 0));
    }
    free_by_offset_[0] = capacity;
  }

  // True only when the live mapping is the memfd-backed MAP_SHARED one —
  // serving a private fallback mapping would SCM_RIGHTS-pass an fd whose
  // pages are NOT the ones the owner writes.
  bool SharedBacked() const { return shared_backed_; }

  ~Store() {
    if (base_ != nullptr && base_ != MAP_FAILED) munmap(base_, capacity_);
    if (fd_ >= 0) close(fd_);
  }

  bool ok() const { return base_ != nullptr && base_ != MAP_FAILED; }

  int CreateObject(const IdKey& id, uint64_t size, uint8_t** out) {
    std::lock_guard<std::mutex> g(mu_);
    if (objects_.count(id)) return -1;
    uint64_t aligned = Align(size == 0 ? 1 : size);
    uint64_t offset;
    if (!Allocate(aligned, &offset)) return -2;
    Entry e;
    e.offset = offset;
    e.size = size;
    e.pin_count = 1;  // pinned until sealed
    e.lru_tick = ++tick_;
    objects_[id] = e;
    used_ += aligned;
    alloc_sizes_[offset] = aligned;
    *out = base_ + offset;
    return 0;
  }

  int Seal(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (!it->second.sealed) {
      it->second.sealed = true;
      it->second.pin_count -= 1;
    }
    return 0;
  }

  int Get(const IdKey& id, uint8_t** out, uint64_t* out_size, int pin) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end() || !it->second.sealed) return -1;
    it->second.lru_tick = ++tick_;
    if (pin) it->second.pin_count += 1;
    *out = base_ + it->second.offset;
    *out_size = it->second.size;
    return 0;
  }

  int Unpin(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (it->second.pin_count > 0) it->second.pin_count -= 1;
    return 0;
  }

  int Delete(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (it->second.pin_count > 0) return -3;  // in use
    Free(it->second.offset);
    objects_.erase(it);
    return 0;
  }

  int Contains(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    return it != objects_.end() && it->second.sealed ? 1 : 0;
  }

  // LRU candidates (sealed, unpinned) totalling at least nbytes of arena.
  uint64_t EvictCandidates(uint64_t nbytes, uint8_t* out_ids, uint64_t max) {
    std::lock_guard<std::mutex> g(mu_);
    std::map<uint64_t, const IdKey*> by_tick;
    for (auto& kv : objects_) {
      if (kv.second.sealed && kv.second.pin_count == 0)
        by_tick[kv.second.lru_tick] = &kv.first;
    }
    uint64_t freed = 0, n = 0;
    for (auto& kv : by_tick) {
      if (freed >= nbytes || n >= max) break;
      const Entry& e = objects_[*kv.second];
      auto it = alloc_sizes_.find(e.offset);
      freed += it != alloc_sizes_.end() ? it->second : e.size;
      std::memcpy(out_ids + n * 16, kv.second->bytes, 16);
      n += 1;
    }
    return freed >= nbytes ? n : (n > 0 ? n : 0);
  }

  void Stats(uint64_t* used, uint64_t* capacity, uint64_t* count) {
    std::lock_guard<std::mutex> g(mu_);
    *used = used_;
    *capacity = capacity_;
    *count = objects_.size();
  }

  // Free an unsealed (aborted) object regardless of its create-pin — the
  // disconnect path for a client that died between CREATE and SEAL.
  int Abort(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (it->second.sealed) return -2;
    Free(it->second.offset);
    objects_.erase(it);
    return 0;
  }

  int Fd() const { return fd_; }
  uint8_t* Base() const { return base_; }

 private:
  static uint64_t Align(uint64_t n) { return (n + 63) & ~uint64_t(63); }

  bool Allocate(uint64_t size, uint64_t* out_offset) {
    // First fit over the ordered free map.
    for (auto it = free_by_offset_.begin(); it != free_by_offset_.end();
         ++it) {
      if (it->second >= size) {
        *out_offset = it->first;
        uint64_t rem = it->second - size;
        uint64_t off = it->first;
        free_by_offset_.erase(it);
        if (rem > 0) free_by_offset_[off + size] = rem;
        return true;
      }
    }
    return false;
  }

  void Free(uint64_t offset) {
    auto sz = alloc_sizes_.find(offset);
    if (sz == alloc_sizes_.end()) return;
    uint64_t size = sz->second;
    alloc_sizes_.erase(sz);
    used_ -= size;
    auto next = free_by_offset_.lower_bound(offset);
    // Coalesce with following free block.
    if (next != free_by_offset_.end() && next->first == offset + size) {
      size += next->second;
      next = free_by_offset_.erase(next);
    }
    // Coalesce with preceding free block.
    if (next != free_by_offset_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        prev->second += size;
        return;
      }
    }
    free_by_offset_[offset] = size;
  }

  std::mutex mu_;
  uint64_t capacity_;
  uint64_t used_ = 0;
  uint64_t tick_ = 0;
  int fd_ = -1;
  bool shared_backed_ = false;
  uint8_t* base_ = nullptr;
  std::unordered_map<IdKey, Entry, IdHash> objects_;
  std::map<uint64_t, uint64_t> free_by_offset_;   // offset -> size
  std::unordered_map<uint64_t, uint64_t> alloc_sizes_;  // offset -> size
};

IdKey MakeKey(const uint8_t* id) {
  IdKey k;
  std::memcpy(k.bytes, id, 16);
  return k;
}

// ---------------------------------------------------------------------------
// UDS wire: request = op(1) + id(16) + arg(8) = 25 bytes;
//           reply   = rc(4) + a(8) + b(8)    = 20 bytes.
// On connect the server first sends capacity(8) with the memfd attached
// via SCM_RIGHTS.
// ---------------------------------------------------------------------------

enum Op : uint8_t {
  OP_CREATE = 1,   // arg=size   -> a=offset
  OP_SEAL = 2,
  OP_GET = 3,      //            -> a=offset, b=size (pins)
  OP_UNPIN = 4,
  OP_DELETE = 5,
  OP_CONTAINS = 6, //            -> rc 1/0
  OP_STATS = 7,    //            -> a=used, b=count
};

constexpr size_t kReqLen = 25;
constexpr size_t kRepLen = 20;

bool ReadExact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteExact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool SendWithFd(int sock, const void* buf, size_t n, int fd) {
  struct msghdr msg = {};
  struct iovec iov = {const_cast<void*>(buf), n};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  char ctrl[CMSG_SPACE(sizeof(int))] = {};
  msg.msg_control = ctrl;
  msg.msg_controllen = sizeof(ctrl);
  struct cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  cm->cmsg_level = SOL_SOCKET;
  cm->cmsg_type = SCM_RIGHTS;
  cm->cmsg_len = CMSG_LEN(sizeof(int));
  std::memcpy(CMSG_DATA(cm), &fd, sizeof(int));
  return sendmsg(sock, &msg, 0) == static_cast<ssize_t>(n);
}

bool RecvWithFd(int sock, void* buf, size_t n, int* out_fd) {
  struct msghdr msg = {};
  struct iovec iov = {buf, n};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  char ctrl[CMSG_SPACE(sizeof(int))] = {};
  msg.msg_control = ctrl;
  msg.msg_controllen = sizeof(ctrl);
  ssize_t r = recvmsg(sock, &msg, 0);
  if (r != static_cast<ssize_t>(n)) return false;
  *out_fd = -1;
  for (struct cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
       cm = CMSG_NXTHDR(&msg, cm)) {
    if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS) {
      std::memcpy(out_fd, CMSG_DATA(cm), sizeof(int));
      break;
    }
  }
  return true;
}

class StoreServer {
 public:
  StoreServer(Store* store, const char* path) : store_(store) {
    listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    struct sockaddr_un addr = {};
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
    unlink(path);
    // Same-user only from the first instant: the arena socket hands out
    // the memfd mapping ALL host object memory, so the socket must never
    // be world-connectable, not even between bind() and a later chmod().
    mode_t prev_umask = umask(0077);
    int rc = bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&addr),
                  sizeof(addr));
    umask(prev_umask);
    if (rc != 0 || listen(listen_fd_, 64) != 0) {
      close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    chmod(path, 0600);  // belt-and-braces on filesystems ignoring umask
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  bool ok() const { return listen_fd_ >= 0; }

  ~StoreServer() {
    stopping_ = true;
    if (listen_fd_ >= 0) {
      shutdown(listen_fd_, SHUT_RDWR);
      close(listen_fd_);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // Kick every parked connection thread out of its blocking read, then
    // wait for the (detached) threads to drain. The wait is UNBOUNDED on
    // purpose: returning early would free this server (and soon the
    // Store) under a thread that still dereferences both — the fds are
    // shut down, so every blocking read/write fails immediately and the
    // only remaining work is mutex-bounded Store cleanup.
    {
      std::unique_lock<std::mutex> g(conns_mu_);
      for (int fd : conn_fds_) shutdown(fd, SHUT_RDWR);
      conns_cv_.wait(g, [this] { return conn_fds_.empty(); });
    }
  }

 private:
  void AcceptLoop() {
    while (!stopping_) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_) return;
        continue;
      }
      {
        std::lock_guard<std::mutex> g(conns_mu_);
        conn_fds_.insert(fd);
      }
      // detached: finished connections self-reap (no per-connection
      // std::thread object accumulating for the server's lifetime). The
      // fd is closed here, under conns_mu_, so the destructor's
      // shutdown() can never hit a recycled descriptor.
      std::thread([this, fd] {
        Serve(fd);
        std::lock_guard<std::mutex> g(conns_mu_);
        close(fd);
        conn_fds_.erase(fd);
        conns_cv_.notify_all();
      }).detach();
    }
  }

  void Serve(int fd) {
    // handshake: capacity + the arena fd
    uint64_t cap, used, count;
    store_->Stats(&used, &cap, &count);
    if (!SendWithFd(fd, &cap, sizeof(cap), store_->Fd())) {
      return;  // wrapper closure closes the fd
    }
    // per-connection bookkeeping for crash rollback
    std::unordered_map<IdKey, int64_t, IdHash> pins;
    std::unordered_map<IdKey, bool, IdHash> unsealed;
    uint8_t req[kReqLen];
    while (!stopping_ && ReadExact(fd, req, kReqLen)) {
      uint8_t op = req[0];
      IdKey id = MakeKey(req + 1);
      uint64_t arg;
      std::memcpy(&arg, req + 17, 8);
      int32_t rc = -1;
      uint64_t a = 0, b = 0;
      switch (op) {
        case OP_CREATE: {
          uint8_t* ptr = nullptr;
          rc = store_->CreateObject(id, arg, &ptr);
          if (rc == 0) {
            a = static_cast<uint64_t>(ptr - store_->Base());
            unsealed[id] = true;
          }
          break;
        }
        case OP_SEAL:
          rc = store_->Seal(id);
          if (rc == 0) unsealed.erase(id);
          break;
        case OP_GET: {
          uint8_t* ptr = nullptr;
          rc = store_->Get(id, &ptr, &b, 1);
          if (rc == 0) {
            a = static_cast<uint64_t>(ptr - store_->Base());
            pins[id] += 1;
          }
          break;
        }
        case OP_UNPIN:
          rc = store_->Unpin(id);
          if (rc == 0 && pins.count(id) && --pins[id] <= 0) pins.erase(id);
          break;
        case OP_DELETE:
          rc = store_->Delete(id);
          break;
        case OP_CONTAINS:
          rc = store_->Contains(id);
          break;
        case OP_STATS: {
          uint64_t cap2;
          store_->Stats(&a, &cap2, &b);
          rc = 0;
          break;
        }
        default:
          rc = -100;
      }
      uint8_t rep[kRepLen];
      std::memcpy(rep, &rc, 4);
      std::memcpy(rep + 4, &a, 8);
      std::memcpy(rep + 12, &b, 8);
      if (!WriteExact(fd, rep, kRepLen)) break;
    }
    // rollback: release this connection's pins, abort half-created objects
    for (auto& kv : pins)
      for (int64_t i = 0; i < kv.second; ++i) store_->Unpin(kv.first);
    for (auto& kv : unsealed) store_->Abort(kv.first);
  }

  Store* store_;
  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex conns_mu_;
  std::condition_variable conns_cv_;
  std::set<int> conn_fds_;
};

std::mutex g_servers_mu;
std::unordered_map<void*, StoreServer*> g_servers;

// -- client ----------------------------------------------------------------

struct StoreClient {
  int sock = -1;
  int arena_fd = -1;
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  std::mutex mu;  // one outstanding request per connection
};

}  // namespace

extern "C" {

void* nps_create(uint64_t capacity) {
  Store* s = new Store(capacity);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void nps_destroy(void* s) {
  {
    std::lock_guard<std::mutex> g(g_servers_mu);
    auto it = g_servers.find(s);
    if (it != g_servers.end()) {
      delete it->second;
      g_servers.erase(it);
    }
  }
  delete static_cast<Store*>(s);
}

// Serve this store's arena over a Unix domain socket (idempotent per
// store). Clients receive the memfd via SCM_RIGHTS and map the same pages.
int nps_serve(void* s, const char* path) {
  std::lock_guard<std::mutex> g(g_servers_mu);
  if (g_servers.count(s)) return 0;
  if (!static_cast<Store*>(s)->SharedBacked()) return -2;  // private fallback
  StoreServer* srv = new StoreServer(static_cast<Store*>(s), path);
  if (!srv->ok()) {
    delete srv;
    return -1;
  }
  g_servers[s] = srv;
  return 0;
}

int nps_create_object(void* s, const uint8_t* id, uint64_t size,
                      uint8_t** out) {
  return static_cast<Store*>(s)->CreateObject(MakeKey(id), size, out);
}

int nps_seal(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Seal(MakeKey(id));
}

int nps_get(void* s, const uint8_t* id, uint8_t** out, uint64_t* out_size,
            int pin) {
  return static_cast<Store*>(s)->Get(MakeKey(id), out, out_size, pin);
}

int nps_unpin(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Unpin(MakeKey(id));
}

int nps_delete(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Delete(MakeKey(id));
}

int nps_contains(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Contains(MakeKey(id));
}

uint64_t nps_evict_candidates(void* s, uint64_t nbytes, uint8_t* out_ids,
                              uint64_t max) {
  return static_cast<Store*>(s)->EvictCandidates(nbytes, out_ids, max);
}

void nps_stats(void* s, uint64_t* used, uint64_t* capacity, uint64_t* count) {
  static_cast<Store*>(s)->Stats(used, capacity, count);
}

int nps_fd(void* s) { return static_cast<Store*>(s)->Fd(); }

// -- client side (same-host peer processes) --------------------------------

void* npc_connect(const char* path) {
  int sock = socket(AF_UNIX, SOCK_STREAM, 0);
  if (sock < 0) return nullptr;
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (connect(sock, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(sock);
    return nullptr;
  }
  uint64_t capacity = 0;
  int fd = -1;
  if (!RecvWithFd(sock, &capacity, sizeof(capacity), &fd) || fd < 0) {
    close(sock);
    return nullptr;
  }
  uint8_t* base = static_cast<uint8_t*>(
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
  if (base == MAP_FAILED) {
    close(fd);
    close(sock);
    return nullptr;
  }
  StoreClient* c = new StoreClient();
  c->sock = sock;
  c->arena_fd = fd;
  c->base = base;
  c->capacity = capacity;
  return c;
}

void npc_close(void* h) {
  StoreClient* c = static_cast<StoreClient*>(h);
  if (c == nullptr) return;
  if (c->base != nullptr) munmap(c->base, c->capacity);
  if (c->arena_fd >= 0) close(c->arena_fd);
  if (c->sock >= 0) close(c->sock);
  delete c;
}

// Close the connection + fd but KEEP the mapping: zero-copy values handed
// out earlier reference these pages; unmapping under them would turn a
// post-shutdown read into a SIGSEGV. The pages are reclaimed at process
// exit (or when the last memfd reference drops).
void npc_detach(void* h) {
  StoreClient* c = static_cast<StoreClient*>(h);
  if (c == nullptr) return;
  if (c->arena_fd >= 0) close(c->arena_fd);
  if (c->sock >= 0) close(c->sock);
  delete c;
}

uint64_t npc_capacity(void* h) {
  return static_cast<StoreClient*>(h)->capacity;
}

namespace {
int ClientCall(StoreClient* c, uint8_t op, const uint8_t* id, uint64_t arg,
               uint64_t* a, uint64_t* b) {
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t req[kReqLen];
  req[0] = op;
  std::memcpy(req + 1, id, 16);
  std::memcpy(req + 17, &arg, 8);
  if (!WriteExact(c->sock, req, kReqLen)) return -101;
  uint8_t rep[kRepLen];
  if (!ReadExact(c->sock, rep, kRepLen)) return -101;
  int32_t rc;
  std::memcpy(&rc, rep, 4);
  if (a != nullptr) std::memcpy(a, rep + 4, 8);
  if (b != nullptr) std::memcpy(b, rep + 12, 8);
  return rc;
}
}  // namespace

// CREATE: on success *out points into the SHARED mapping — write payload
// bytes there, then npc_seal.
int npc_create_object(void* h, const uint8_t* id, uint64_t size,
                      uint8_t** out) {
  StoreClient* c = static_cast<StoreClient*>(h);
  uint64_t off = 0;
  int rc = ClientCall(c, OP_CREATE, id, size, &off, nullptr);
  if (rc == 0) *out = c->base + off;
  return rc;
}

int npc_seal(void* h, const uint8_t* id) {
  return ClientCall(static_cast<StoreClient*>(h), OP_SEAL, id, 0, nullptr,
                    nullptr);
}

int npc_get(void* h, const uint8_t* id, uint8_t** out, uint64_t* out_size,
            int pin) {
  (void)pin;  // server GET always pins; npc_unpin releases
  StoreClient* c = static_cast<StoreClient*>(h);
  uint64_t off = 0, size = 0;
  int rc = ClientCall(c, OP_GET, id, 0, &off, &size);
  if (rc == 0) {
    *out = c->base + off;
    *out_size = size;
  }
  return rc;
}

int npc_unpin(void* h, const uint8_t* id) {
  return ClientCall(static_cast<StoreClient*>(h), OP_UNPIN, id, 0, nullptr,
                    nullptr);
}

int npc_delete(void* h, const uint8_t* id) {
  return ClientCall(static_cast<StoreClient*>(h), OP_DELETE, id, 0, nullptr,
                    nullptr);
}

int npc_contains(void* h, const uint8_t* id) {
  return ClientCall(static_cast<StoreClient*>(h), OP_CONTAINS, id, 0,
                    nullptr, nullptr);
}

void npc_stats(void* h, uint64_t* used, uint64_t* capacity,
               uint64_t* count) {
  StoreClient* c = static_cast<StoreClient*>(h);
  ClientCall(c, OP_STATS, reinterpret_cast<const uint8_t*>(
                              "\0\0\0\0\0\0\0\0\0\0\0\0\0\0\0\0"),
             0, used, count);
  *capacity = c->capacity;
}

}  // extern "C"
