// Native shared-memory object store: the plasma equivalent
// (reference: src/ray/object_manager/plasma/store.h, object_lifecycle_manager.h,
// plasma_allocator.h, eviction_policy.h), redesigned for the host-granular
// TPU runtime:
//
// - One mmap'd arena per host backed by memfd (sealed host-object bytes).
//   The arena is MAP_SHARED so future helper processes can map the same fd;
//   in the single-owner-process runtime, workers are threads and read the
//   buffers zero-copy through pointers handed across the C ABI.
// - Boundary-coalescing free-list allocator (dlmalloc.cc's role, simplified:
//   first-fit over an ordered free map with neighbor coalescing on free).
// - LRU eviction over sealed, unpinned objects (eviction_policy.h LRUCache):
//   the caller asks for candidates, spills them (local_object_manager.h:99
//   SpillObjects is the Python side), then deletes.
// - create -> write -> seal lifecycle with get() blocking handled in Python
//   (the store itself is non-blocking; CreateRequestQueue backpressure is
//   expressed as the -NOSPACE error code the caller turns into spilling).
//
// C ABI only — bound from Python via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

#include <sys/mman.h>
#include <unistd.h>

#ifdef __linux__
#include <sys/syscall.h>
#endif

namespace {

struct IdKey {
  uint8_t bytes[16];
  bool operator==(const IdKey& o) const {
    return std::memcmp(bytes, o.bytes, 16) == 0;
  }
};

struct IdHash {
  size_t operator()(const IdKey& k) const {
    uint64_t h;
    std::memcpy(&h, k.bytes, 8);
    uint64_t l;
    std::memcpy(&l, k.bytes + 8, 8);
    return static_cast<size_t>(h ^ (l * 0x9e3779b97f4a7c15ULL));
  }
};

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;
  int64_t pin_count = 0;
  uint64_t lru_tick = 0;
  bool sealed = false;
};

class Store {
 public:
  explicit Store(uint64_t capacity) : capacity_(capacity) {
#ifdef __linux__
    fd_ = static_cast<int>(syscall(SYS_memfd_create, "ray_tpu_plasma", 0));
#else
    fd_ = -1;
#endif
    if (fd_ >= 0 && ftruncate(fd_, static_cast<off_t>(capacity)) == 0) {
      base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                         PROT_READ | PROT_WRITE, MAP_SHARED,
                                         fd_, 0));
    }
    if (base_ == MAP_FAILED || base_ == nullptr) {
      // Fallback: anonymous private mapping (no cross-process sharing).
      base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                         PROT_READ | PROT_WRITE,
                                         MAP_PRIVATE | MAP_ANONYMOUS, -1, 0));
    }
    free_by_offset_[0] = capacity;
  }

  ~Store() {
    if (base_ != nullptr && base_ != MAP_FAILED) munmap(base_, capacity_);
    if (fd_ >= 0) close(fd_);
  }

  bool ok() const { return base_ != nullptr && base_ != MAP_FAILED; }

  int CreateObject(const IdKey& id, uint64_t size, uint8_t** out) {
    std::lock_guard<std::mutex> g(mu_);
    if (objects_.count(id)) return -1;
    uint64_t aligned = Align(size == 0 ? 1 : size);
    uint64_t offset;
    if (!Allocate(aligned, &offset)) return -2;
    Entry e;
    e.offset = offset;
    e.size = size;
    e.pin_count = 1;  // pinned until sealed
    e.lru_tick = ++tick_;
    objects_[id] = e;
    used_ += aligned;
    alloc_sizes_[offset] = aligned;
    *out = base_ + offset;
    return 0;
  }

  int Seal(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (!it->second.sealed) {
      it->second.sealed = true;
      it->second.pin_count -= 1;
    }
    return 0;
  }

  int Get(const IdKey& id, uint8_t** out, uint64_t* out_size, int pin) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end() || !it->second.sealed) return -1;
    it->second.lru_tick = ++tick_;
    if (pin) it->second.pin_count += 1;
    *out = base_ + it->second.offset;
    *out_size = it->second.size;
    return 0;
  }

  int Unpin(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (it->second.pin_count > 0) it->second.pin_count -= 1;
    return 0;
  }

  int Delete(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    if (it->second.pin_count > 0) return -3;  // in use
    Free(it->second.offset);
    objects_.erase(it);
    return 0;
  }

  int Contains(const IdKey& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    return it != objects_.end() && it->second.sealed ? 1 : 0;
  }

  // LRU candidates (sealed, unpinned) totalling at least nbytes of arena.
  uint64_t EvictCandidates(uint64_t nbytes, uint8_t* out_ids, uint64_t max) {
    std::lock_guard<std::mutex> g(mu_);
    std::map<uint64_t, const IdKey*> by_tick;
    for (auto& kv : objects_) {
      if (kv.second.sealed && kv.second.pin_count == 0)
        by_tick[kv.second.lru_tick] = &kv.first;
    }
    uint64_t freed = 0, n = 0;
    for (auto& kv : by_tick) {
      if (freed >= nbytes || n >= max) break;
      const Entry& e = objects_[*kv.second];
      auto it = alloc_sizes_.find(e.offset);
      freed += it != alloc_sizes_.end() ? it->second : e.size;
      std::memcpy(out_ids + n * 16, kv.second->bytes, 16);
      n += 1;
    }
    return freed >= nbytes ? n : (n > 0 ? n : 0);
  }

  void Stats(uint64_t* used, uint64_t* capacity, uint64_t* count) {
    std::lock_guard<std::mutex> g(mu_);
    *used = used_;
    *capacity = capacity_;
    *count = objects_.size();
  }

  int Fd() const { return fd_; }

 private:
  static uint64_t Align(uint64_t n) { return (n + 63) & ~uint64_t(63); }

  bool Allocate(uint64_t size, uint64_t* out_offset) {
    // First fit over the ordered free map.
    for (auto it = free_by_offset_.begin(); it != free_by_offset_.end();
         ++it) {
      if (it->second >= size) {
        *out_offset = it->first;
        uint64_t rem = it->second - size;
        uint64_t off = it->first;
        free_by_offset_.erase(it);
        if (rem > 0) free_by_offset_[off + size] = rem;
        return true;
      }
    }
    return false;
  }

  void Free(uint64_t offset) {
    auto sz = alloc_sizes_.find(offset);
    if (sz == alloc_sizes_.end()) return;
    uint64_t size = sz->second;
    alloc_sizes_.erase(sz);
    used_ -= size;
    auto next = free_by_offset_.lower_bound(offset);
    // Coalesce with following free block.
    if (next != free_by_offset_.end() && next->first == offset + size) {
      size += next->second;
      next = free_by_offset_.erase(next);
    }
    // Coalesce with preceding free block.
    if (next != free_by_offset_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        prev->second += size;
        return;
      }
    }
    free_by_offset_[offset] = size;
  }

  std::mutex mu_;
  uint64_t capacity_;
  uint64_t used_ = 0;
  uint64_t tick_ = 0;
  int fd_ = -1;
  uint8_t* base_ = nullptr;
  std::unordered_map<IdKey, Entry, IdHash> objects_;
  std::map<uint64_t, uint64_t> free_by_offset_;   // offset -> size
  std::unordered_map<uint64_t, uint64_t> alloc_sizes_;  // offset -> size
};

IdKey MakeKey(const uint8_t* id) {
  IdKey k;
  std::memcpy(k.bytes, id, 16);
  return k;
}

}  // namespace

extern "C" {

void* nps_create(uint64_t capacity) {
  Store* s = new Store(capacity);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void nps_destroy(void* s) { delete static_cast<Store*>(s); }

int nps_create_object(void* s, const uint8_t* id, uint64_t size,
                      uint8_t** out) {
  return static_cast<Store*>(s)->CreateObject(MakeKey(id), size, out);
}

int nps_seal(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Seal(MakeKey(id));
}

int nps_get(void* s, const uint8_t* id, uint8_t** out, uint64_t* out_size,
            int pin) {
  return static_cast<Store*>(s)->Get(MakeKey(id), out, out_size, pin);
}

int nps_unpin(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Unpin(MakeKey(id));
}

int nps_delete(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Delete(MakeKey(id));
}

int nps_contains(void* s, const uint8_t* id) {
  return static_cast<Store*>(s)->Contains(MakeKey(id));
}

uint64_t nps_evict_candidates(void* s, uint64_t nbytes, uint8_t* out_ids,
                              uint64_t max) {
  return static_cast<Store*>(s)->EvictCandidates(nbytes, out_ids, max);
}

void nps_stats(void* s, uint64_t* used, uint64_t* capacity, uint64_t* count) {
  static_cast<Store*>(s)->Stats(used, capacity, count);
}

int nps_fd(void* s) { return static_cast<Store*>(s)->Fd(); }

}  // extern "C"
