// C++ worker/driver API for the ray_tpu cluster.
//
// The role of the reference's C++ worker API (src/ray/core_worker C++
// bindings + cpp/ frontend), shaped for this runtime's cross-language
// contract: a C++ program joins an existing cluster as a DRIVER — it
// discovers daemons through the state service, submits tasks that invoke
// Python functions registered by name (register_named_function), passes
// arguments as JSON, and receives JSON results inline in the task reply
// (reply-as-completion, so no C++ unpickler is needed anywhere).
//
// Speaks the native wire protocol: 4-byte big-endian frame length +
// raytpu.Envelope, with the AUTH first-frame handshake. Link with the
// protoc-generated raytpu.pb.cc (see build.py build_cpp_worker_demo).
//
// The library surface (RayTpuClient) is header-free on purpose: this file
// compiles either into the demo binary (RAYTPU_CPP_DEMO_MAIN) or can be
// #included / linked into a user's C++ program.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <stdexcept>
#include <string>
#include <vector>

#include "raytpu.pb.h"

namespace raytpu_cpp {

class Connection {
 public:
  Connection(const std::string& host, int port, const std::string& token) {
    // getaddrinfo: cluster addresses are routinely hostnames, not
    // numeric IPs (e.g. the autoscaler's --address=head:6379)
    struct addrinfo hints = {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 ||
        res == nullptr)
      throw std::runtime_error("cannot resolve " + host);
    int err = -1;
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) {
        err = 0;
        break;
      }
      close(fd_);
      fd_ = -1;
    }
    freeaddrinfo(res);
    if (err != 0 || fd_ < 0)
      throw std::runtime_error("connect to " + host + " failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (!token.empty()) {
      raytpu::Envelope auth;
      auth.set_seq(0);
      auth.set_method(raytpu::AUTH);
      auth.set_body(token);
      SendEnvelope(auth);
    }
  }

  ~Connection() {
    if (fd_ >= 0) close(fd_);
  }

  raytpu::Envelope Call(raytpu::Method method, const std::string& body) {
    raytpu::Envelope req;
    req.set_seq(++seq_);
    req.set_method(method);
    req.set_body(body);
    SendEnvelope(req);
    // replies can interleave with pushes on this protocol; a plain driver
    // connection sees only its own replies (no subscriptions) — read
    // frames until our seq answers
    while (true) {
      raytpu::Envelope rep = ReadEnvelope();
      if (rep.seq() == req.seq()) {
        if (!rep.error().empty())
          throw std::runtime_error("rpc error: " + rep.error());
        return rep;
      }
    }
  }

 private:
  void SendEnvelope(const raytpu::Envelope& env) {
    std::string payload;
    env.SerializeToString(&payload);
    uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
    std::string frame(reinterpret_cast<char*>(&len), 4);
    frame += payload;
    WriteExact(frame.data(), frame.size());
  }

  // Matches rpc.py MAX_FRAME: reject oversized declared lengths BEFORE
  // allocating, so a corrupt/malicious peer cannot drive huge allocations.
  static constexpr uint32_t kMaxFrame = 1u << 31;

  raytpu::Envelope ReadEnvelope() {
    uint8_t hdr[4];
    ReadExact(hdr, 4);
    uint32_t len = (uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                   (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]);
    if (len > kMaxFrame)
      throw std::runtime_error("frame exceeds MAX_FRAME");
    std::string buf(len, '\0');
    ReadExact(buf.data(), len);
    raytpu::Envelope env;
    if (!env.ParseFromString(buf))
      throw std::runtime_error("bad envelope frame");
    return env;
  }

  void WriteExact(const void* data, size_t n) {
    const char* p = static_cast<const char*>(data);
    while (n > 0) {
      ssize_t r = write(fd_, p, n);
      if (r <= 0) throw std::runtime_error("connection write failed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  void ReadExact(void* data, size_t n) {
    char* p = static_cast<char*>(data);
    while (n > 0) {
      ssize_t r = read(fd_, p, n);
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  uint64_t seq_ = 0;
};

struct HostPort {
  std::string host;
  int port;
};

inline HostPort SplitAddr(const std::string& addr) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos)
    throw std::runtime_error("address must be host:port: " + addr);
  return {addr.substr(0, pos), std::stoi(addr.substr(pos + 1))};
}

class TaskCaller;
class ActorCreator;

class RayTpuClient {
 public:
  RayTpuClient(const std::string& state_addr, const std::string& token)
      : token_(token), rng_(std::random_device{}()) {
    auto hp = SplitAddr(state_addr);
    state_ = std::make_unique<Connection>(hp.host, hp.port, token_);
    job_id_ = RandomBytes(4);
  }

  // Typed API entry points (defined after TaskCaller/ActorCreator).
  TaskCaller Task(const std::string& function_name);
  ActorCreator Actor(const std::string& registered_class);

  std::string RandomHex(size_t n) {
    static const char* hex = "0123456789abcdef";
    std::lock_guard<std::mutex> g(rng_mu_);
    std::string out;
    std::uniform_int_distribution<int> d(0, 15);
    for (size_t i = 0; i < n; ++i) out += hex[d(rng_)];
    return out;
  }

  // -- cluster introspection ------------------------------------------
  std::vector<raytpu::NodeInfo> ListNodes() {
    raytpu::Envelope rep = StateCall(raytpu::LIST_NODES, "");
    raytpu::ListNodesReply nodes;
    nodes.ParseFromString(rep.body());
    std::vector<raytpu::NodeInfo> out;
    for (const auto& n : nodes.nodes()) out.push_back(n);
    return out;
  }

  // -- KV (cross-language shared state) -------------------------------
  bool KvPut(const std::string& key, const std::string& value) {
    raytpu::KvPutRequest req;
    req.set_key(key);
    req.set_value(value);
    req.set_overwrite(true);
    std::string body;
    req.SerializeToString(&body);
    raytpu::KvPutReply kp;
    kp.ParseFromString(StateCall(raytpu::KV_PUT, body).body());
    return kp.added();
  }

  std::string KvGet(const std::string& key) {
    raytpu::KvGetRequest req;
    req.set_key(key);
    std::string body;
    req.SerializeToString(&body);
    raytpu::KvGetReply kg;
    kg.ParseFromString(StateCall(raytpu::KV_GET, body).body());
    return kg.found() ? kg.value() : "";
  }

  // -- cross-language task submission ---------------------------------
  // Invoke a Python function registered via register_named_function with
  // JSON positional args; returns the JSON-encoded result. Throws on task
  // error (message from the daemon's language-neutral error_message).
  std::string SubmitTask(const std::string& function_name,
                         const std::string& args_json) {
    // One node-list fetch; prefer non-head daemons, fall back to any.
    // "spillback" is a routine scheduling reply (the daemon's resources
    // are momentarily busy), not a failure: rotate through candidate
    // daemons like the Python client does.
    auto nodes = ListNodes();
    std::vector<std::string> candidates;
    for (const auto& n : nodes)
      if (n.alive() && !n.address().empty() && !n.is_head())
        candidates.push_back(n.address());
    for (const auto& n : nodes)
      if (n.alive() && !n.address().empty() && n.is_head())
        candidates.push_back(n.address());
    if (candidates.empty())
      throw std::runtime_error("no alive daemons in the cluster");

    raytpu::TaskSpecMsg spec;
    std::string task_id = RandomBytes(16);
    spec.set_task_id(task_id);
    spec.set_job_id(job_id_);
    spec.set_function_name(function_name);
    spec.set_named_function(function_name);
    spec.set_args_json(args_json);
    spec.set_json_results(true);
    spec.set_num_returns(1);
    // return id: task_id(16) + little-endian index 0 (ids.py ObjectID)
    std::string rid = task_id + std::string(4, '\0');
    spec.add_return_ids(rid);
    (*spec.mutable_resources()->mutable_amounts())["CPU"] = 1.0;
    std::string body;
    spec.SerializeToString(&body);

    // time-based budget: rotate immediately within a round, sleep 100ms
    // after each fruitless full round, give up after ~10s wall clock
    // regardless of the candidate count
    const auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(10);
    for (size_t attempt = 0;
         std::chrono::steady_clock::now() < deadline; ++attempt) {
      const std::string& daemon_addr =
          candidates[attempt % candidates.size()];
      auto hp = SplitAddr(daemon_addr);
      Connection daemon(hp.host, hp.port, token_);
      raytpu::Envelope rep = daemon.Call(raytpu::PUSH_TASK, body);
      raytpu::PushTaskReply out;
      out.ParseFromString(rep.body());
      if (out.status() == "spillback") {
        if ((attempt + 1) % candidates.size() == 0) usleep(100 * 1000);
        continue;
      }
      if (out.status() != "ok")
        throw std::runtime_error("task not admitted: " + out.status());
      if (!out.error_message().empty())
        throw std::runtime_error("task failed: " + out.error_message());
      if (out.inline_results_size() > 0 && out.inline_(0))
        return out.inline_results(0);
      throw std::runtime_error("no inline result (json_results expected)");
    }
    throw std::runtime_error("cluster busy: task spilled back "
                             "repeatedly");
  }

 private:
  // The state connection is shared by every thread of the typed API
  // (ObjectRef futures submit concurrently): one call at a time.
  raytpu::Envelope StateCall(raytpu::Method m, const std::string& body) {
    std::lock_guard<std::mutex> g(state_mu_);
    return state_->Call(m, body);
  }

  std::string RandomBytes(size_t n) {
    std::lock_guard<std::mutex> g(rng_mu_);
    std::string out(n, '\0');
    std::uniform_int_distribution<int> d(0, 255);
    for (size_t i = 0; i < n; ++i)
      out[i] = static_cast<char>(d(rng_));
    return out;
  }

  std::string token_;
  std::string job_id_;
  std::unique_ptr<Connection> state_;
  std::mutex state_mu_;
  std::mutex rng_mu_;
  std::mt19937_64 rng_;
};

}  // namespace raytpu_cpp

// ---------------------------------------------------------------------------
// Typed task/actor API — the surface of the reference's C++ frontend
// (cpp/include/ray/api/task_caller.h:1, actor_creator.h:1,
// object_ref.h:1), on this runtime's cross-language contract:
//
//   raytpu_cpp::RayTpuClient client(addr, token);
//   auto ref = client.Task("py_fn").Remote<int64_t>(2, 3);   // non-blocking
//   int64_t five = ref.Get();                                // typed wait
//   auto counter = client.Actor("Counter").Remote(10);       // named class
//   int64_t v = counter.Call<int64_t>("add", 5).Get();
//   counter.Kill();
//
// Arguments are serialized with typed JSON encoders (no stringly-typed
// payload assembly in user code); results decode into the ObjectRef's
// type parameter. Execution stays on the Python daemons — the typed
// layer is the driver-side contract, matching the runtime's
// "Python defines, any language drives" model (worker.py
// register_named_actor_class).
// ---------------------------------------------------------------------------

#include <cmath>
#include <cstdio>
#include <future>
#include <limits>
#include <sstream>

namespace raytpu_cpp {

// ---- typed JSON encode ----------------------------------------------------
inline void JsonEncode(std::ostringstream& o, int64_t v) { o << v; }
inline void JsonEncode(std::ostringstream& o, int v) { o << v; }
inline void JsonEncode(std::ostringstream& o, double v) {
  if (!std::isfinite(v))
    throw std::runtime_error("JSON cannot carry inf/nan arguments");
  o.precision(std::numeric_limits<double>::max_digits10);
  o << v;
}
inline void JsonEncode(std::ostringstream& o, bool v) {
  o << (v ? "true" : "false");
}
inline void JsonEncode(std::ostringstream& o, const std::string& v) {
  o << '"';
  for (char c : v) {
    unsigned char u = static_cast<unsigned char>(c);
    switch (c) {
      case '"': o << "\\\""; break;
      case '\\': o << "\\\\"; break;
      case '\n': o << "\\n"; break;
      case '\t': o << "\\t"; break;
      case '\r': o << "\\r"; break;
      case '\b': o << "\\b"; break;
      case '\f': o << "\\f"; break;
      default:
        if (u < 0x20) {  // remaining C0 controls: strict JSON requires \u
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", u);
          o << buf;
        } else {
          o << c;  // UTF-8 bytes pass through verbatim
        }
    }
  }
  o << '"';
}
inline void JsonEncode(std::ostringstream& o, const char* v) {
  JsonEncode(o, std::string(v));
}
template <typename T>
inline void JsonEncode(std::ostringstream& o, const std::vector<T>& v) {
  o << '[';
  for (size_t i = 0; i < v.size(); ++i) {
    if (i) o << ", ";
    JsonEncode(o, v[i]);
  }
  o << ']';
}

inline void EncodeArgsInto(std::ostringstream&) {}
template <typename A, typename... Rest>
inline void EncodeArgsInto(std::ostringstream& o, A&& a, Rest&&... rest) {
  JsonEncode(o, std::forward<A>(a));
  if (sizeof...(rest)) o << ", ";
  EncodeArgsInto(o, std::forward<Rest>(rest)...);
}
template <typename... Args>
inline std::string EncodeArgs(Args&&... args) {
  std::ostringstream o;
  o << '[';
  EncodeArgsInto(o, std::forward<Args>(args)...);
  o << ']';
  return o.str();
}

// ---- typed JSON decode (scalars + flat arrays — the named-function
// result contract; nested structures stay strings for the caller) ---------
inline std::string JsonTrim(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\n\r");
  size_t b = s.find_last_not_of(" \t\n\r");
  return a == std::string::npos ? "" : s.substr(a, b - a + 1);
}

template <typename T>
T JsonDecode(const std::string& json);

template <>
inline int64_t JsonDecode<int64_t>(const std::string& json) {
  return std::stoll(JsonTrim(json));
}
template <>
inline double JsonDecode<double>(const std::string& json) {
  return std::stod(JsonTrim(json));
}
template <>
inline bool JsonDecode<bool>(const std::string& json) {
  std::string t = JsonTrim(json);
  if (t == "true") return true;
  if (t == "false") return false;
  throw std::runtime_error("not a JSON bool: " + t);
}
template <>
inline std::string JsonDecode<std::string>(const std::string& json) {
  std::string t = JsonTrim(json);
  if (t.size() < 2 || t.front() != '"' || t.back() != '"')
    throw std::runtime_error("not a JSON string: " + t);
  std::string out;
  for (size_t i = 1; i + 1 < t.size(); ++i) {
    if (t[i] != '\\' || i + 2 >= t.size()) {
      out += t[i];
      continue;
    }
    char n = t[++i];
    switch (n) {
      case 'n': out += '\n'; break;
      case 't': out += '\t'; break;
      case 'r': out += '\r'; break;
      case 'b': out += '\b'; break;
      case 'f': out += '\f'; break;
      case '"': out += '"'; break;
      case '\\': out += '\\'; break;
      case '/': out += '/'; break;
      case 'u': {
        // \uXXXX (Python json.dumps default ensure_ascii escapes all
        // non-ASCII this way) -> UTF-8. Surrogate pairs for astral
        // planes are combined when both halves are present.
        if (i + 4 >= t.size())
          throw std::runtime_error("truncated \\u escape");
        unsigned cp = std::stoul(t.substr(i + 1, 4), nullptr, 16);
        i += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF && i + 6 < t.size() &&
            t[i + 1] == '\\' && t[i + 2] == 'u') {
          unsigned lo = std::stoul(t.substr(i + 3, 4), nullptr, 16);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            i += 6;
          }
        }
        if (cp < 0x80) {
          out += static_cast<char>(cp);
        } else if (cp < 0x800) {
          out += static_cast<char>(0xC0 | (cp >> 6));
          out += static_cast<char>(0x80 | (cp & 0x3F));
        } else if (cp < 0x10000) {
          out += static_cast<char>(0xE0 | (cp >> 12));
          out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          out += static_cast<char>(0x80 | (cp & 0x3F));
        } else {
          out += static_cast<char>(0xF0 | (cp >> 18));
          out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
          out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          out += static_cast<char>(0x80 | (cp & 0x3F));
        }
        break;
      }
      default: out += n;
    }
  }
  return out;
}
template <>
inline std::vector<int64_t> JsonDecode<std::vector<int64_t>>(
    const std::string& json) {
  std::string t = JsonTrim(json);
  if (t.size() < 2 || t.front() != '[' || t.back() != ']')
    throw std::runtime_error("not a JSON array: " + t);
  std::vector<int64_t> out;
  std::stringstream ss(t.substr(1, t.size() - 2));
  std::string item;
  while (std::getline(ss, item, ','))
    if (!JsonTrim(item).empty()) out.push_back(std::stoll(JsonTrim(item)));
  return out;
}

// ---- ObjectRef<T> (object_ref.h role) ------------------------------------
template <typename T>
class ObjectRef {
 public:
  explicit ObjectRef(std::shared_future<std::string> json)
      : json_(std::move(json)) {}
  // Blocks for the task reply, decodes into T. Task errors rethrow here
  // (the future carries the submission thread's exception).
  T Get() const { return JsonDecode<T>(json_.get()); }
  // Raw JSON, for nested results the scalar decoders don't cover.
  std::string GetJson() const { return json_.get(); }

 private:
  std::shared_future<std::string> json_;
};

// ---- TaskCaller (task_caller.h role) -------------------------------------
class RayTpuClient;  // fwd

class TaskCaller {
 public:
  TaskCaller(RayTpuClient* client, std::string fn)
      : client_(client), fn_(std::move(fn)) {}
  // Non-blocking: submission runs on its own thread; the ObjectRef's
  // future resolves with the task's JSON result.
  template <typename R, typename... Args>
  ObjectRef<R> Remote(Args&&... args);

 private:
  RayTpuClient* client_;
  std::string fn_;
};

// ---- actors (actor_creator.h / actor_handle.h roles) ---------------------
class ActorHandle {
 public:
  ActorHandle(RayTpuClient* client, std::string name)
      : client_(client), name_(std::move(name)) {}
  const std::string& Name() const { return name_; }
  template <typename R, typename... Args>
  ObjectRef<R> Call(const std::string& method, Args&&... args);
  void Kill();

 private:
  RayTpuClient* client_;
  std::string name_;
};

class ActorCreator {
 public:
  ActorCreator(RayTpuClient* client, std::string cls)
      : client_(client), cls_(std::move(cls)) {}
  // Creates a NAMED actor from the Python-registered class; the handle
  // routes calls by that name from any connection.
  template <typename... Args>
  ActorHandle Remote(Args&&... args);

 private:
  RayTpuClient* client_;
  std::string cls_;
};

// ---- definitions (RayTpuClient is complete here) --------------------------
inline TaskCaller RayTpuClient::Task(const std::string& function_name) {
  return TaskCaller(this, function_name);
}
inline ActorCreator RayTpuClient::Actor(const std::string& cls) {
  return ActorCreator(this, cls);
}

template <typename R, typename... Args>
ObjectRef<R> TaskCaller::Remote(Args&&... args) {
  std::string args_json = EncodeArgs(std::forward<Args>(args)...);
  RayTpuClient* c = client_;
  std::string fn = fn_;
  return ObjectRef<R>(std::async(std::launch::async, [c, fn, args_json] {
                        return c->SubmitTask(fn, args_json);
                      }).share());
}

template <typename... Args>
ActorHandle ActorCreator::Remote(Args&&... args) {
  // Creation blocks until the daemon's reply: the returned handle must
  // be immediately callable (the name is registered at creation time).
  std::string name = cls_ + "-" + client_->RandomHex(12);
  client_->SubmitTask("__actor_new__::" + cls_,
                      EncodeArgs(name, std::forward<Args>(args)...));
  return ActorHandle(client_, name);
}

template <typename R, typename... Args>
ObjectRef<R> ActorHandle::Call(const std::string& method, Args&&... args) {
  std::string args_json =
      EncodeArgs(name_, method, std::forward<Args>(args)...);
  RayTpuClient* c = client_;
  return ObjectRef<R>(std::async(std::launch::async, [c, args_json] {
                        return c->SubmitTask("__actor_call__", args_json);
                      }).share());
}

inline void ActorHandle::Kill() {
  client_->SubmitTask("__actor_kill__", EncodeArgs(name_));
}

}  // namespace raytpu_cpp

#ifdef RAYTPU_CPP_DEMO_MAIN
// Demo driver: raytpu_cpp_demo <state_addr> [token]
//   - lists nodes
//   - round-trips the KV
//   - calls the Python-registered named function "cpp_add" with [2, 3]
//
// Typed mode: raytpu_cpp_demo <state_addr> --typed [token]
//   - Task("cpp_add").Remote<int64_t>(2, 3) -> ObjectRef<int64_t>
//   - Actor("Counter").Remote(10) -> typed method calls -> Kill()
int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <state_addr> [--typed] [token]\n", argv[0]);
    return 2;
  }
  bool typed = argc > 2 && std::string(argv[2]) == "--typed";
  std::string token = typed ? (argc > 3 ? argv[3] : "")
                            : (argc > 2 ? argv[2] : "");
  try {
    raytpu_cpp::RayTpuClient client(argv[1], token);
    if (typed) {
      auto sum = client.Task("cpp_add").Remote<int64_t>(2, 3);
      printf("typed_add=%lld\n", static_cast<long long>(sum.Get()));
      auto counter = client.Actor("Counter").Remote(int64_t{10});
      printf("actor_name=%s\n", counter.Name().c_str());
      auto a = counter.Call<int64_t>("add", int64_t{5});
      printf("counter_add=%lld\n", static_cast<long long>(a.Get()));
      auto b = counter.Call<int64_t>("add", int64_t{7});
      printf("counter_add2=%lld\n", static_cast<long long>(b.Get()));
      auto t = counter.Call<int64_t>("total");
      printf("counter_total=%lld\n", static_cast<long long>(t.Get()));
      counter.Kill();
      printf("typed-ok\n");
      return 0;
    }
    auto nodes = client.ListNodes();
    printf("nodes=%zu\n", nodes.size());
    client.KvPut("cpp-kv-key", "from-cpp");
    printf("kv=%s\n", client.KvGet("cpp-kv-key").c_str());
    std::string result = client.SubmitTask("cpp_add", "[2, 3]");
    printf("cpp_add(2,3)=%s\n", result.c_str());
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
#endif
