// C++ worker/driver API for the ray_tpu cluster.
//
// The role of the reference's C++ worker API (src/ray/core_worker C++
// bindings + cpp/ frontend), shaped for this runtime's cross-language
// contract: a C++ program joins an existing cluster as a DRIVER — it
// discovers daemons through the state service, submits tasks that invoke
// Python functions registered by name (register_named_function), passes
// arguments as JSON, and receives JSON results inline in the task reply
// (reply-as-completion, so no C++ unpickler is needed anywhere).
//
// Speaks the native wire protocol: 4-byte big-endian frame length +
// raytpu.Envelope, with the AUTH first-frame handshake. Link with the
// protoc-generated raytpu.pb.cc (see build.py build_cpp_worker_demo).
//
// The library surface (RayTpuClient) is header-free on purpose: this file
// compiles either into the demo binary (RAYTPU_CPP_DEMO_MAIN) or can be
// #included / linked into a user's C++ program.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstring>
#include <memory>
#include <random>
#include <stdexcept>
#include <string>
#include <vector>

#include "raytpu.pb.h"

namespace raytpu_cpp {

class Connection {
 public:
  Connection(const std::string& host, int port, const std::string& token) {
    // getaddrinfo: cluster addresses are routinely hostnames, not
    // numeric IPs (e.g. the autoscaler's --address=head:6379)
    struct addrinfo hints = {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 ||
        res == nullptr)
      throw std::runtime_error("cannot resolve " + host);
    int err = -1;
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) {
        err = 0;
        break;
      }
      close(fd_);
      fd_ = -1;
    }
    freeaddrinfo(res);
    if (err != 0 || fd_ < 0)
      throw std::runtime_error("connect to " + host + " failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (!token.empty()) {
      raytpu::Envelope auth;
      auth.set_seq(0);
      auth.set_method(raytpu::AUTH);
      auth.set_body(token);
      SendEnvelope(auth);
    }
  }

  ~Connection() {
    if (fd_ >= 0) close(fd_);
  }

  raytpu::Envelope Call(raytpu::Method method, const std::string& body) {
    raytpu::Envelope req;
    req.set_seq(++seq_);
    req.set_method(method);
    req.set_body(body);
    SendEnvelope(req);
    // replies can interleave with pushes on this protocol; a plain driver
    // connection sees only its own replies (no subscriptions) — read
    // frames until our seq answers
    while (true) {
      raytpu::Envelope rep = ReadEnvelope();
      if (rep.seq() == req.seq()) {
        if (!rep.error().empty())
          throw std::runtime_error("rpc error: " + rep.error());
        return rep;
      }
    }
  }

 private:
  void SendEnvelope(const raytpu::Envelope& env) {
    std::string payload;
    env.SerializeToString(&payload);
    uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
    std::string frame(reinterpret_cast<char*>(&len), 4);
    frame += payload;
    WriteExact(frame.data(), frame.size());
  }

  // Matches rpc.py MAX_FRAME: reject oversized declared lengths BEFORE
  // allocating, so a corrupt/malicious peer cannot drive huge allocations.
  static constexpr uint32_t kMaxFrame = 1u << 31;

  raytpu::Envelope ReadEnvelope() {
    uint8_t hdr[4];
    ReadExact(hdr, 4);
    uint32_t len = (uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                   (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]);
    if (len > kMaxFrame)
      throw std::runtime_error("frame exceeds MAX_FRAME");
    std::string buf(len, '\0');
    ReadExact(buf.data(), len);
    raytpu::Envelope env;
    if (!env.ParseFromString(buf))
      throw std::runtime_error("bad envelope frame");
    return env;
  }

  void WriteExact(const void* data, size_t n) {
    const char* p = static_cast<const char*>(data);
    while (n > 0) {
      ssize_t r = write(fd_, p, n);
      if (r <= 0) throw std::runtime_error("connection write failed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  void ReadExact(void* data, size_t n) {
    char* p = static_cast<char*>(data);
    while (n > 0) {
      ssize_t r = read(fd_, p, n);
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  uint64_t seq_ = 0;
};

struct HostPort {
  std::string host;
  int port;
};

inline HostPort SplitAddr(const std::string& addr) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos)
    throw std::runtime_error("address must be host:port: " + addr);
  return {addr.substr(0, pos), std::stoi(addr.substr(pos + 1))};
}

class RayTpuClient {
 public:
  RayTpuClient(const std::string& state_addr, const std::string& token)
      : token_(token), rng_(std::random_device{}()) {
    auto hp = SplitAddr(state_addr);
    state_ = std::make_unique<Connection>(hp.host, hp.port, token_);
    job_id_ = RandomBytes(4);
  }

  // -- cluster introspection ------------------------------------------
  std::vector<raytpu::NodeInfo> ListNodes() {
    raytpu::Envelope rep = state_->Call(raytpu::LIST_NODES, "");
    raytpu::ListNodesReply nodes;
    nodes.ParseFromString(rep.body());
    std::vector<raytpu::NodeInfo> out;
    for (const auto& n : nodes.nodes()) out.push_back(n);
    return out;
  }

  // -- KV (cross-language shared state) -------------------------------
  bool KvPut(const std::string& key, const std::string& value) {
    raytpu::KvPutRequest req;
    req.set_key(key);
    req.set_value(value);
    req.set_overwrite(true);
    std::string body;
    req.SerializeToString(&body);
    raytpu::KvPutReply kp;
    kp.ParseFromString(state_->Call(raytpu::KV_PUT, body).body());
    return kp.added();
  }

  std::string KvGet(const std::string& key) {
    raytpu::KvGetRequest req;
    req.set_key(key);
    std::string body;
    req.SerializeToString(&body);
    raytpu::KvGetReply kg;
    kg.ParseFromString(state_->Call(raytpu::KV_GET, body).body());
    return kg.found() ? kg.value() : "";
  }

  // -- cross-language task submission ---------------------------------
  // Invoke a Python function registered via register_named_function with
  // JSON positional args; returns the JSON-encoded result. Throws on task
  // error (message from the daemon's language-neutral error_message).
  std::string SubmitTask(const std::string& function_name,
                         const std::string& args_json) {
    // One node-list fetch; prefer non-head daemons, fall back to any.
    // "spillback" is a routine scheduling reply (the daemon's resources
    // are momentarily busy), not a failure: rotate through candidate
    // daemons like the Python client does.
    auto nodes = ListNodes();
    std::vector<std::string> candidates;
    for (const auto& n : nodes)
      if (n.alive() && !n.address().empty() && !n.is_head())
        candidates.push_back(n.address());
    for (const auto& n : nodes)
      if (n.alive() && !n.address().empty() && n.is_head())
        candidates.push_back(n.address());
    if (candidates.empty())
      throw std::runtime_error("no alive daemons in the cluster");

    raytpu::TaskSpecMsg spec;
    std::string task_id = RandomBytes(16);
    spec.set_task_id(task_id);
    spec.set_job_id(job_id_);
    spec.set_function_name(function_name);
    spec.set_named_function(function_name);
    spec.set_args_json(args_json);
    spec.set_json_results(true);
    spec.set_num_returns(1);
    // return id: task_id(16) + little-endian index 0 (ids.py ObjectID)
    std::string rid = task_id + std::string(4, '\0');
    spec.add_return_ids(rid);
    (*spec.mutable_resources()->mutable_amounts())["CPU"] = 1.0;
    std::string body;
    spec.SerializeToString(&body);

    // time-based budget: rotate immediately within a round, sleep 100ms
    // after each fruitless full round, give up after ~10s wall clock
    // regardless of the candidate count
    const auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(10);
    for (size_t attempt = 0;
         std::chrono::steady_clock::now() < deadline; ++attempt) {
      const std::string& daemon_addr =
          candidates[attempt % candidates.size()];
      auto hp = SplitAddr(daemon_addr);
      Connection daemon(hp.host, hp.port, token_);
      raytpu::Envelope rep = daemon.Call(raytpu::PUSH_TASK, body);
      raytpu::PushTaskReply out;
      out.ParseFromString(rep.body());
      if (out.status() == "spillback") {
        if ((attempt + 1) % candidates.size() == 0) usleep(100 * 1000);
        continue;
      }
      if (out.status() != "ok")
        throw std::runtime_error("task not admitted: " + out.status());
      if (!out.error_message().empty())
        throw std::runtime_error("task failed: " + out.error_message());
      if (out.inline_results_size() > 0 && out.inline_(0))
        return out.inline_results(0);
      throw std::runtime_error("no inline result (json_results expected)");
    }
    throw std::runtime_error("cluster busy: task spilled back "
                             "repeatedly");
  }

 private:
  std::string RandomBytes(size_t n) {
    std::string out(n, '\0');
    std::uniform_int_distribution<int> d(0, 255);
    for (size_t i = 0; i < n; ++i)
      out[i] = static_cast<char>(d(rng_));
    return out;
  }

  std::string token_;
  std::string job_id_;
  std::unique_ptr<Connection> state_;
  std::mt19937_64 rng_;
};

}  // namespace raytpu_cpp

#ifdef RAYTPU_CPP_DEMO_MAIN
// Demo driver: raytpu_cpp_demo <state_addr> [token]
//   - lists nodes
//   - round-trips the KV
//   - calls the Python-registered named function "cpp_add" with [2, 3]
int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <state_addr> [token]\n", argv[0]);
    return 2;
  }
  std::string token = argc > 2 ? argv[2] : "";
  try {
    raytpu_cpp::RayTpuClient client(argv[1], token);
    auto nodes = client.ListNodes();
    printf("nodes=%zu\n", nodes.size());
    client.KvPut("cpp-kv-key", "from-cpp");
    printf("kv=%s\n", client.KvGet("cpp-kv-key").c_str());
    std::string result = client.SubmitTask("cpp_add", "[2, 3]");
    printf("cpp_add(2,3)=%s\n", result.c_str());
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
#endif
