// Native scheduling policy kernels.
//
// The C++ half of the scheduler (reference: src/ray/raylet/scheduling/
// policy/hybrid_scheduling_policy.h:48 pack-then-spread with top-k
// randomization, spread_scheduling_policy.h:27, fixed_point.h resource
// arithmetic). The Python policy layer flattens node snapshots into
// dense matrices and calls these kernels; semantics are kept identical
// to the Python fallback so the two paths are interchangeable.
//
// C ABI only — bound via ctypes (no pybind11 in the image).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

inline bool Fits(const double* avail_row, const double* request,
                 int64_t n_res) {
  for (int64_t r = 0; r < n_res; ++r) {
    if (request[r] > 0 && avail_row[r] < request[r] - 1e-9) return false;
  }
  return true;
}

inline double Utilization(const double* avail_row, const double* total_row,
                          int64_t n_res) {
  // Max utilization across resource dimensions (resources.py:142).
  double best = 0.0;
  for (int64_t r = 0; r < n_res; ++r) {
    double tot = total_row[r];
    if (tot <= 0) continue;
    double used = tot - avail_row[r];
    double u = used / tot;
    if (u > best) best = u;
  }
  return best;
}

struct Scored {
  double score;
  int not_preferred;
  int64_t index;
};

}  // namespace

extern "C" {

// Hybrid pack-then-spread: returns the selected node index, or -1 when no
// alive node fits. rng_draw in [0, 2^63) supplies the top-k randomness so
// the caller's seeded generator stays the source of determinism.
int64_t sched_hybrid_select(const double* available, const double* total,
                            const uint8_t* alive, const double* request,
                            int64_t n_nodes, int64_t n_res,
                            int64_t preferred_idx, double spread_threshold,
                            double top_k_fraction, int64_t rng_draw) {
  std::vector<Scored> scored;
  scored.reserve(n_nodes);
  for (int64_t i = 0; i < n_nodes; ++i) {
    if (!alive[i]) continue;
    const double* avail_row = available + i * n_res;
    if (!Fits(avail_row, request, n_res)) continue;
    double util = Utilization(avail_row, total + i * n_res, n_res);
    double score = util < spread_threshold ? 0.0 : util;
    scored.push_back({score, i == preferred_idx ? 0 : 1, i});
  }
  if (scored.empty()) return -1;
  std::sort(scored.begin(), scored.end(),
            [](const Scored& a, const Scored& b) {
              if (a.score != b.score) return a.score < b.score;
              if (a.not_preferred != b.not_preferred)
                return a.not_preferred < b.not_preferred;
              return a.index < b.index;
            });
  int64_t k = static_cast<int64_t>(scored.size() * top_k_fraction);
  if (k < 1) k = 1;
  return scored[rng_draw % k].index;
}

// Round-robin spread: returns the selected node index advancing from
// *cursor, or -1. The caller owns the cursor (SpreadPolicy state).
int64_t sched_spread_select(const double* available, const uint8_t* alive,
                            const double* request, int64_t n_nodes,
                            int64_t n_res, int64_t cursor) {
  std::vector<int64_t> feasible;
  feasible.reserve(n_nodes);
  for (int64_t i = 0; i < n_nodes; ++i) {
    if (!alive[i]) continue;
    if (Fits(available + i * n_res, request, n_res)) feasible.push_back(i);
  }
  if (feasible.empty()) return -1;
  return feasible[cursor % static_cast<int64_t>(feasible.size())];
}

}  // extern "C"
