// Block-wise int8 quantization kernels for the collective compression tier.
//
// C ABI consumed via ctypes (ray_tpu/collective/quantization.py); built on
// first use by _native/build.py with vectorization flags — the -O2 default
// does not vectorize the absmax scan and loses to numpy, while -O3
// -march=native turns both loops into packed max/convert and beats the
// fused numpy path ~3x on one core.
//
// Scheme (EQuARX-style dynamic block quantization, arxiv 2506.17615):
// each contiguous block of `block` floats gets one f32 scale =
// absmax/127; payload is round-to-nearest int8 clamped to ±127. The
// tail block may be short. Dequantization fused into the reduction
// (rtq_q8_dequant_add) keeps accumulation at full precision.

#include <cstdint>
#include <cstring>

extern "C" {

// Per-block absmax is found with unsigned-integer compares: for IEEE-754
// floats, |a| <= |b|  <=>  (bits(a) & 0x7fffffff) <= (bits(b) & 0x7fffffff),
// so the scan is a packed AND+MAX with no float semantics for the
// vectorizer to worry about. A block whose absmax is Inf/NaN poisons its
// scale to -1 (payload zeroed); the Python layer rejects negative scales
// loudly instead of shipping silent garbage.
void rtq_q8_quantize(const float* __restrict x, int64_t n, int64_t block,
                     int8_t* __restrict q, float* __restrict scales) {
    const uint32_t* xb = (const uint32_t*)x;
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        int64_t lo = b * block;
        int64_t hi = lo + block < n ? lo + block : n;
        uint32_t am = 0;
        for (int64_t i = lo; i < hi; ++i) {
            uint32_t a = xb[i] & 0x7fffffffu;
            if (a > am) am = a;
        }
        float amf;
        std::memcpy(&amf, &am, 4);
        float scale = amf / 127.0f;
        scales[b] = scale;
        if (scale == 0.0f || am >= 0x7f800000u) {
            if (am >= 0x7f800000u) scales[b] = -1.0f;
            std::memset(q + lo, 0, (size_t)(hi - lo));
            continue;
        }
        float inv = 1.0f / scale;
        for (int64_t i = lo; i < hi; ++i) {
            float v = x[i] * inv;
            q[i] = (int8_t)__builtin_rintf(v);
        }
    }
}

// acc[i] += scale[block(i)] * q[i] — the fused dequant+accumulate that
// keeps the reduction at f32 (quantized ranks never sum in int8).
void rtq_q8_dequant_add(const int8_t* __restrict q,
                        const float* __restrict scales, int64_t n,
                        int64_t block, float* __restrict acc) {
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        int64_t lo = b * block;
        int64_t hi = lo + block < n ? lo + block : n;
        float s = scales[b];
        for (int64_t i = lo; i < hi; ++i) acc[i] += s * (float)q[i];
    }
}

void rtq_q8_dequant(const int8_t* __restrict q,
                    const float* __restrict scales, int64_t n,
                    int64_t block, float* __restrict out) {
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        int64_t lo = b * block;
        int64_t hi = lo + block < n ? lo + block : n;
        float s = scales[b];
        for (int64_t i = lo; i < hi; ++i) out[i] = s * (float)q[i];
    }
}

}  // extern "C"
