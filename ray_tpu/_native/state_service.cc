// raytpu_state_service — the cluster state service daemon.
//
// The C++ control-plane process playing the reference's GCS server role
// (src/ray/gcs/gcs_server/gcs_server.h:70, gcs_server_main.cc): node table
// with heartbeat failure detection (gcs_heartbeat_manager.h:36), internal
// KV (gcs_kv_manager.h), actor/placement-group/job tables
// (gcs_actor_manager.h, gcs_placement_group_mgr.h), an object directory,
// and long-poll-free pubsub (src/ray/pubsub/) — all over the framed
// protobuf protocol defined in ray_tpu/protocol/raytpu.proto instead of
// gRPC: a single epoll loop multiplexes every client on one socket each.
//
// Persistence (gcs_table_storage.h role): every mutating RPC is appended
// to a journal; periodic snapshots compact it. On restart the tables are
// rebuilt, so named actors stay resolvable and nodes resume with their
// next heartbeat (the reference's GCS fault-tolerance contract, tested by
// python/ray/tests/test_gcs_fault_tolerance.py — ours by
// tests/test_state_service.py::test_head_restart_rebuilds_state).
//
// Build: ray_tpu/_native/build.py::build_state_service (g++ + libprotobuf).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/timerfd.h>
#include <time.h>
#include <unistd.h>

#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "gen/raytpu.pb.h"

namespace {

volatile sig_atomic_t g_stop = 0;
void on_signal(int) { g_stop = 1; }

double now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec * 1e3 + ts.tv_nsec / 1e6;
}

double mono_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1e3 + ts.tv_nsec / 1e6;
}

void set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

std::string frame(const raytpu::Envelope& env) {
  std::string payload;
  env.SerializeToString(&payload);
  std::string out(4, '\0');
  uint32_t n = payload.size();
  out[0] = (n >> 24) & 0xff;
  out[1] = (n >> 16) & 0xff;
  out[2] = (n >> 8) & 0xff;
  out[3] = n & 0xff;
  out += payload;
  return out;
}

struct Conn {
  int fd = -1;
  bool authed = false;
  std::string rbuf;
  std::string wbuf;
  std::set<std::string> channels;  // pubsub subscriptions
};

class StateService {
 public:
  StateService(int port, const std::string& host, const std::string& data_dir,
               double hb_timeout_ms, double snapshot_interval_s,
               const std::string& auth_token)
      : auth_token_(auth_token),
        host_(host),
        port_(port),
        data_dir_(data_dir),
        hb_timeout_ms_(hb_timeout_ms),
        snapshot_interval_s_(snapshot_interval_s) {}

  int Run(const std::string& port_file) {
    if (!data_dir_.empty()) {
      mkdir(data_dir_.c_str(), 0755);
      LoadPersisted();
      cluster_epoch_++;
      WriteSnapshot();  // persist the epoch bump immediately
      OpenJournal();
    }
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port_);
    if (inet_pton(AF_INET, host_.c_str(), &addr.sin_addr) != 1) {
      fprintf(stderr, "bad host %s\n", host_.c_str());
      return 1;
    }
    if (bind(listen_fd_, (sockaddr*)&addr, sizeof(addr)) != 0) {
      perror("bind");
      return 1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, (sockaddr*)&addr, &alen);
    port_ = ntohs(addr.sin_port);
    listen(listen_fd_, 128);
    set_nonblocking(listen_fd_);

    if (!port_file.empty()) {
      std::string tmp = port_file + ".tmp";
      FILE* f = fopen(tmp.c_str(), "w");
      if (f) {
        fprintf(f, "%d\n", port_);
        fclose(f);
        rename(tmp.c_str(), port_file.c_str());
      }
    }
    fprintf(stderr, "[state_service] listening on %s:%d epoch=%llu\n",
            host_.c_str(), port_, (unsigned long long)cluster_epoch_);

    epfd_ = epoll_create1(0);
    AddFd(listen_fd_, EPOLLIN);

    timer_fd_ = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
    struct itimerspec its {};
    its.it_interval.tv_nsec = 250 * 1000000;  // 250ms sweep
    its.it_value.tv_nsec = 250 * 1000000;
    timerfd_settime(timer_fd_, 0, &its, nullptr);
    AddFd(timer_fd_, EPOLLIN);

    std::vector<epoll_event> events(256);
    double last_snapshot = mono_ms();
    while (!g_stop) {
      int n = epoll_wait(epfd_, events.data(), events.size(), 500);
      if (n < 0) {
        if (errno == EINTR) continue;
        perror("epoll_wait");
        break;
      }
      for (int i = 0; i < n; i++) {
        int fd = events[i].data.fd;
        uint32_t ev = events[i].events;
        if (fd == listen_fd_) {
          Accept();
        } else if (fd == timer_fd_) {
          uint64_t expirations;
          while (read(timer_fd_, &expirations, 8) > 0) {
          }
          SweepHeartbeats();
          if (!data_dir_.empty() &&
              mono_ms() - last_snapshot > snapshot_interval_s_ * 1e3) {
            WriteSnapshot();
            last_snapshot = mono_ms();
          }
        } else {
          if (ev & (EPOLLHUP | EPOLLERR)) {
            CloseConn(fd);
            continue;
          }
          if (ev & EPOLLIN) HandleReadable(fd);
          if (conns_.count(fd) && (ev & EPOLLOUT)) FlushWrites(fd);
        }
      }
    }
    if (!data_dir_.empty()) WriteSnapshot();
    fprintf(stderr, "[state_service] shutting down\n");
    return 0;
  }

 private:
  // ------------------------------------------------------------- event loop

  void AddFd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  }

  void ModFd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &ev);
  }

  void Accept() {
    while (true) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      set_nonblocking(fd);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      conns_[fd] = Conn{};
      conns_[fd].fd = fd;
      AddFd(fd, EPOLLIN);
    }
  }

  void CloseConn(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    conns_.erase(it);
    epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
  }

  void HandleReadable(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    Conn& c = it->second;
    char buf[1 << 16];
    while (true) {
      ssize_t n = recv(fd, buf, sizeof(buf), 0);
      if (n > 0) {
        c.rbuf.append(buf, n);
        // Unauthenticated peers get a tiny buffer allowance. Stop
        // draining (don't close yet: the allowance may hold a valid AUTH
        // frame pipelined ahead of a large first request — the parse
        // loop below consumes it and flips c.authed). Level-triggered
        // epoll re-delivers whatever is left in the socket.
        if (!auth_token_.empty() && !c.authed &&
            c.rbuf.size() > (1u << 16) + 4) break;
      } else if (n == 0) {
        CloseConn(fd);
        return;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        CloseConn(fd);
        return;
      }
    }
    // Parse complete frames.
    size_t off = 0;
    while (c.rbuf.size() - off >= 4) {
      const unsigned char* p = (const unsigned char*)c.rbuf.data() + off;
      uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                     (uint32_t(p[2]) << 8) | uint32_t(p[3]);
      if (len > (1u << 30)) {  // 1 GiB sanity cap
        CloseConn(fd);
        return;
      }
      // An unauthenticated peer may only send the tiny AUTH frame —
      // don't let it commit us to buffering a huge declared length.
      if (!auth_token_.empty() && !c.authed && len > (1u << 16)) {
        fprintf(stderr, "[state_service] oversized pre-auth frame\n");
        CloseConn(fd);
        return;
      }
      if (c.rbuf.size() - off - 4 < len) break;
      raytpu::Envelope env;
      if (env.ParseFromArray(c.rbuf.data() + off + 4, len)) {
        if (!auth_token_.empty() && !c.authed) {
          // Opening frame must be AUTH with the shared secret
          // (constant-time compare); otherwise drop the socket before
          // anything reaches a handler.
          if (env.method() != raytpu::AUTH ||
              !ConstantTimeEq(env.body(), auth_token_)) {
            fprintf(stderr, "[state_service] rejected unauthenticated "
                            "connection\n");
            CloseConn(fd);
            return;
          }
          c.authed = true;
        } else if (env.method() == raytpu::AUTH) {
          // redundant re-auth: ignore
        } else {
          Dispatch(fd, env);
          if (!conns_.count(fd)) return;  // handler closed us
        }
      } else if (!auth_token_.empty() && !c.authed) {
        // pre-auth frames must parse as a valid AUTH Envelope; garbage
        // gets the socket dropped, not skipped
        CloseConn(fd);
        return;
      }
      off += 4 + len;
    }
    if (off > 0) c.rbuf.erase(0, off);
    // Parse consumed everything it could; a peer still unauthenticated
    // with an over-allowance buffer is streaming garbage, not an AUTH
    // frame — drop it (anti pre-auth OOM).
    if (!auth_token_.empty() && !c.authed &&
        c.rbuf.size() > (1u << 16) + 4) {
      fprintf(stderr, "[state_service] pre-auth buffer overflow\n");
      CloseConn(fd);
      return;
    }
  }

  void SendTo(int fd, const raytpu::Envelope& env) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    it->second.wbuf += frame(env);
    FlushWrites(fd);
  }

  void FlushWrites(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    Conn& c = it->second;
    while (!c.wbuf.empty()) {
      ssize_t n = send(fd, c.wbuf.data(), c.wbuf.size(), MSG_NOSIGNAL);
      if (n > 0) {
        c.wbuf.erase(0, n);
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          ModFd(fd, EPOLLIN | EPOLLOUT);
          return;
        }
        CloseConn(fd);
        return;
      }
    }
    ModFd(fd, EPOLLIN);
  }

  // ------------------------------------------------------------ dispatching

  void Reply(int fd, const raytpu::Envelope& req,
             const google::protobuf::Message& msg) {
    raytpu::Envelope env;
    env.set_seq(req.seq());
    env.set_method(req.method());
    env.set_reply(true);
    std::string body;
    msg.SerializeToString(&body);
    env.set_body(body);
    SendTo(fd, env);
  }

  void ReplyError(int fd, const raytpu::Envelope& req, const std::string& e) {
    raytpu::Envelope env;
    env.set_seq(req.seq());
    env.set_method(req.method());
    env.set_reply(true);
    env.set_error(e);
    SendTo(fd, env);
  }

  void Journal(uint32_t method, const std::string& body) {
    if (journal_ == nullptr) return;
    raytpu::JournalRecord rec;
    rec.set_method(method);
    rec.set_body(body);
    rec.set_ts_ms(now_ms());
    std::string payload;
    rec.SerializeToString(&payload);
    uint32_t n = payload.size();
    unsigned char hdr[4] = {(unsigned char)((n >> 24) & 0xff),
                            (unsigned char)((n >> 16) & 0xff),
                            (unsigned char)((n >> 8) & 0xff),
                            (unsigned char)(n & 0xff)};
    fwrite(hdr, 1, 4, journal_);
    fwrite(payload.data(), 1, n, journal_);
    fflush(journal_);
  }

  void Publish(const std::string& channel, const std::string& kind,
               const std::string& payload) {
    raytpu::Event ev;
    ev.set_channel(channel);
    ev.set_kind(kind);
    ev.set_payload(payload);
    ev.set_ts_ms(now_ms());
    raytpu::Envelope env;
    env.set_seq(0);
    env.set_method(raytpu::PUBLISH);
    std::string body;
    ev.SerializeToString(&body);
    env.set_body(body);
    std::vector<int> fds;
    for (auto& [fd, c] : conns_) {
      if (c.channels.count(channel)) fds.push_back(fd);
    }
    for (int fd : fds) SendTo(fd, env);
    counters_["published"]++;
  }

  // Applies a mutating method to the tables. `live` is false during journal
  // replay (no fd, no pubsub, no re-journaling).
  static bool ConstantTimeEq(const std::string& a, const std::string& b) {
    if (a.size() != b.size()) return false;
    unsigned char acc = 0;
    for (size_t i = 0; i < a.size(); i++) acc |= (a[i] ^ b[i]);
    return acc == 0;
  }

  void Dispatch(int fd, const raytpu::Envelope& env) {
    counters_["rpc_total"]++;
    switch (env.method()) {
      case raytpu::REGISTER_NODE:
        return HandleRegisterNode(fd, env);
      case raytpu::HEARTBEAT:
        return HandleHeartbeat(fd, env);
      case raytpu::LIST_NODES:
        return HandleListNodes(fd, env);
      case raytpu::MARK_NODE_DEAD:
        return HandleMarkNodeDead(fd, env);
      case raytpu::DRAIN_NODE:
        return HandleDrainNode(fd, env);
      case raytpu::KV_PUT:
        return HandleKvPut(fd, env);
      case raytpu::KV_GET:
        return HandleKvGet(fd, env);
      case raytpu::KV_DEL:
        return HandleKvDel(fd, env);
      case raytpu::KV_KEYS:
        return HandleKvKeys(fd, env);
      case raytpu::SUBSCRIBE:
        return HandleSubscribe(fd, env);
      case raytpu::PUBLISH:
        return HandlePublish(fd, env);
      case raytpu::ADD_LOCATION:
        return HandleAddLocation(fd, env);
      case raytpu::REMOVE_LOCATION:
        return HandleRemoveLocation(fd, env);
      case raytpu::GET_LOCATIONS:
        return HandleGetLocations(fd, env);
      case raytpu::REGISTER_ACTOR:
      case raytpu::UPDATE_ACTOR:
        return HandleUpsertActor(fd, env);
      case raytpu::GET_ACTOR:
        return HandleGetActor(fd, env);
      case raytpu::GET_NAMED_ACTOR:
        return HandleGetNamedActor(fd, env);
      case raytpu::LIST_ACTORS:
        return HandleListActors(fd, env);
      case raytpu::REGISTER_PG:
      case raytpu::UPDATE_PG:
        return HandleUpsertPg(fd, env);
      case raytpu::REMOVE_PG:
        return HandleRemovePg(fd, env);
      case raytpu::LIST_PGS:
        return HandleListPgs(fd, env);
      case raytpu::REGISTER_JOB:
        return HandleRegisterJob(fd, env);
      case raytpu::LIST_JOBS:
        return HandleListJobs(fd, env);
      case raytpu::STATE_STATS:
        return HandleStats(fd, env);
      case raytpu::CHECKPOINT: {
        if (!data_dir_.empty()) WriteSnapshot();
        raytpu::Empty e;
        return Reply(fd, env, e);
      }
      case raytpu::PING: {
        raytpu::PingReply r;
        r.set_time_ms(now_ms());
        return Reply(fd, env, r);
      }
      default:
        return ReplyError(fd, env, "unknown method");
    }
  }

  // ------------------------------------------------------------- node table

  void ApplyRegisterNode(const raytpu::RegisterNodeRequest& req) {
    raytpu::NodeInfo info = req.info();
    info.set_alive(true);
    info.set_last_heartbeat_ms(now_ms());
    // A (re-)registration is a fresh lifecycle: any stale DRAINING/DRAINED
    // marker from a previous incarnation of this node id is cleared.
    info.clear_state();
    info.clear_drain_deadline_ms();
    info.clear_drain_reason();
    nodes_[info.node_id()] = info;
    hb_deadline_[info.node_id()] = mono_ms() + hb_timeout_ms_;
  }

  void HandleRegisterNode(int fd, const raytpu::Envelope& env) {
    raytpu::RegisterNodeRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad RegisterNodeRequest");
    ApplyRegisterNode(req);
    Journal(raytpu::REGISTER_NODE, env.body());
    // Publish the applied copy (alive=true, heartbeat stamped), not the
    // raw request — subscribers cache this NodeInfo in their views.
    std::string info_bytes;
    nodes_[req.info().node_id()].SerializeToString(&info_bytes);
    Publish("nodes", "NODE_ADDED", info_bytes);
    raytpu::RegisterNodeReply rep;
    rep.set_server_time_ms(now_ms());
    rep.set_cluster_epoch(cluster_epoch_);
    Reply(fd, env, rep);
  }

  void HandleHeartbeat(int fd, const raytpu::Envelope& env) {
    raytpu::HeartbeatRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad HeartbeatRequest");
    raytpu::HeartbeatReply rep;
    // Clock-sync beacon: always stamped, even on recognized=false, so a
    // re-registering node keeps a fresh offset estimate.
    rep.set_server_time_ms(now_ms());
    auto it = nodes_.find(req.node_id());
    if (it == nodes_.end() || !it->second.alive()) {
      rep.set_recognized(false);  // node must re-register
    } else {
      rep.set_recognized(true);
      // Drain signal rides the ack: the node learns it is DRAINING even
      // when the NODE_DRAINING pubsub push was lost or predates its
      // subscription.
      if (!it->second.state().empty()) {
        rep.set_node_state(it->second.state());
        rep.set_drain_deadline_ms(it->second.drain_deadline_ms());
        rep.set_drain_reason(it->second.drain_reason());
      }
      it->second.set_last_heartbeat_ms(now_ms());
      if (req.has_available()) {
        // Delta broadcast (ray_syncer role): CHANGED availability pushes
        // a NODE_RESOURCES event to every subscriber immediately, so
        // schedulers track capacity at heartbeat latency without
        // polling ListNodes; unchanged heartbeats publish nothing.
        // Entry-wise map compare: serialized-bytes comparison is
        // order-dependent for protobuf maps and would false-positive on
        // every heartbeat with 2+ resource entries.
        const auto& prev = it->second.available().amounts();
        const auto& next = req.available().amounts();
        bool changed = prev.size() != next.size();
        if (!changed) {
          for (const auto& [k, v] : next) {
            auto pit = prev.find(k);
            if (pit == prev.end() || pit->second != v) {
              changed = true;
              break;
            }
          }
        }
        *it->second.mutable_available() = req.available();
        if (changed) {
          std::string info_bytes;
          it->second.SerializeToString(&info_bytes);
          Publish("nodes", "NODE_RESOURCES", info_bytes);
        }
      }
      hb_deadline_[req.node_id()] = mono_ms() + hb_timeout_ms_;
    }
    Reply(fd, env, rep);
  }

  void HandleListNodes(int fd, const raytpu::Envelope& env) {
    raytpu::ListNodesReply rep;
    for (auto& [id, info] : nodes_) *rep.add_nodes() = info;
    Reply(fd, env, rep);
  }

  void ApplyMarkNodeDead(const raytpu::MarkNodeDeadRequest& req) {
    auto it = nodes_.find(req.node_id());
    if (it != nodes_.end()) {
      it->second.set_alive(false);
      it->second.set_death_reason(req.reason());
      // A node that died while DRAINING completed (or forfeited) its
      // lifecycle: terminal state is DRAINED either way — the drain
      // orchestrator's mark_node_dead and a mid-drain heartbeat timeout
      // are distinguished by death_reason, not state.
      if (it->second.state() == "DRAINING")
        it->second.set_state("DRAINED");
    }
    hb_deadline_.erase(req.node_id());
    // Objects on a dead node are gone.
    for (auto dit = obj_dir_.begin(); dit != obj_dir_.end();) {
      dit->second.erase(req.node_id());
      if (dit->second.empty()) {
        obj_sizes_.erase(dit->first);
        dit = obj_dir_.erase(dit);
      } else {
        ++dit;
      }
    }
  }

  void MarkDead(const std::string& node_id, const std::string& reason) {
    raytpu::MarkNodeDeadRequest req;
    req.set_node_id(node_id);
    req.set_reason(reason);
    ApplyMarkNodeDead(req);
    std::string body;
    req.SerializeToString(&body);
    Journal(raytpu::MARK_NODE_DEAD, body);
    // Subscribers parse the event payload as NodeInfo (same shape as
    // NODE_ADDED) so they get the dead node's address for addr-keyed
    // cleanup, not just its id.
    std::string info_bytes;
    auto it = nodes_.find(node_id);
    if (it != nodes_.end()) {
      it->second.SerializeToString(&info_bytes);
    } else {
      raytpu::NodeInfo info;
      info.set_node_id(node_id);
      info.set_alive(false);
      info.set_death_reason(reason);
      info.SerializeToString(&info_bytes);
    }
    Publish("nodes", "NODE_DEAD", info_bytes);
    counters_["nodes_dead"]++;
  }

  void ApplyDrainNode(const raytpu::DrainNodeRequest& req) {
    auto it = nodes_.find(req.node_id());
    if (it == nodes_.end() || !it->second.alive()) return;
    it->second.set_state("DRAINING");
    it->second.set_drain_reason(req.reason());
    it->second.set_drain_deadline_ms(now_ms() + req.deadline_s() * 1e3);
    // Heartbeats keep flowing while draining; the sweep still catches a
    // node that dies mid-drain (MarkDead flips DRAINING -> DRAINED).
  }

  void HandleDrainNode(int fd, const raytpu::Envelope& env) {
    raytpu::DrainNodeRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad DrainNodeRequest");
    auto it = nodes_.find(req.node_id());
    if (it == nodes_.end())
      return ReplyError(fd, env, "unknown node");
    if (!it->second.alive())
      return ReplyError(fd, env, "node already dead");
    bool was_draining = it->second.state() == "DRAINING";
    ApplyDrainNode(req);
    // Idempotent: a second drain request (watcher + operator racing)
    // refreshes reason/deadline but is only journaled/published once per
    // transition so subscribers see one NODE_DRAINING per lifecycle.
    if (!was_draining) {
      Journal(raytpu::DRAIN_NODE, env.body());
      std::string info_bytes;
      it->second.SerializeToString(&info_bytes);
      Publish("nodes", "NODE_DRAINING", info_bytes);
      counters_["nodes_draining"]++;
    }
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleMarkNodeDead(int fd, const raytpu::Envelope& env) {
    raytpu::MarkNodeDeadRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad MarkNodeDeadRequest");
    MarkDead(req.node_id(), req.reason());
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void SweepHeartbeats() {
    double now = mono_ms();
    std::vector<std::string> dead;
    for (auto& [id, deadline] : hb_deadline_) {
      if (now > deadline) dead.push_back(id);
    }
    for (auto& id : dead) MarkDead(id, "heartbeat timeout");
  }

  // --------------------------------------------------------------------- kv

  void HandleKvPut(int fd, const raytpu::Envelope& env) {
    raytpu::KvPutRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad KvPutRequest");
    auto& ns = kv_[req.ns()];
    raytpu::KvPutReply rep;
    if (!req.overwrite() && ns.count(req.key())) {
      rep.set_added(false);
    } else {
      ns[req.key()] = req.value();
      rep.set_added(true);
      Journal(raytpu::KV_PUT, env.body());
      Publish("kv:" + req.ns(), "PUT", req.key());
    }
    Reply(fd, env, rep);
  }

  void HandleKvGet(int fd, const raytpu::Envelope& env) {
    raytpu::KvGetRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad KvGetRequest");
    raytpu::KvGetReply rep;
    auto nit = kv_.find(req.ns());
    if (nit != kv_.end()) {
      auto kit = nit->second.find(req.key());
      if (kit != nit->second.end()) {
        rep.set_found(true);
        rep.set_value(kit->second);
      }
    }
    Reply(fd, env, rep);
  }

  void HandleKvDel(int fd, const raytpu::Envelope& env) {
    raytpu::KvDelRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad KvDelRequest");
    raytpu::KvDelReply rep;
    auto nit = kv_.find(req.ns());
    if (nit != kv_.end()) rep.set_deleted(nit->second.erase(req.key()) > 0);
    if (rep.deleted()) Journal(raytpu::KV_DEL, env.body());
    Reply(fd, env, rep);
  }

  void HandleKvKeys(int fd, const raytpu::Envelope& env) {
    raytpu::KvKeysRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad KvKeysRequest");
    raytpu::KvKeysReply rep;
    auto nit = kv_.find(req.ns());
    if (nit != kv_.end()) {
      for (auto& [k, v] : nit->second) {
        if (k.rfind(req.prefix(), 0) == 0) rep.add_keys(k);
      }
    }
    Reply(fd, env, rep);
  }

  // ----------------------------------------------------------------- pubsub

  void HandleSubscribe(int fd, const raytpu::Envelope& env) {
    raytpu::SubscribeRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad SubscribeRequest");
    auto it = conns_.find(fd);
    if (it != conns_.end()) {
      for (auto& ch : req.channels()) it->second.channels.insert(ch);
    }
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandlePublish(int fd, const raytpu::Envelope& env) {
    raytpu::PublishRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad PublishRequest");
    Publish(req.event().channel(), req.event().kind(), req.event().payload());
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  // ------------------------------------------------------- object directory

  void HandleAddLocation(int fd, const raytpu::Envelope& env) {
    raytpu::ObjectLocRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad ObjectLocRequest");
    obj_dir_[req.object_id()].insert(req.node_id());
    if (req.size() > 0) obj_sizes_[req.object_id()] = req.size();
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleRemoveLocation(int fd, const raytpu::Envelope& env) {
    raytpu::ObjectLocRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad ObjectLocRequest");
    auto it = obj_dir_.find(req.object_id());
    if (it != obj_dir_.end()) {
      it->second.erase(req.node_id());
      if (it->second.empty()) {
        obj_dir_.erase(it);
        obj_sizes_.erase(req.object_id());
      }
    }
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleGetLocations(int fd, const raytpu::Envelope& env) {
    raytpu::GetLocationsRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad GetLocationsRequest");
    raytpu::GetLocationsReply rep;
    auto it = obj_dir_.find(req.object_id());
    if (it != obj_dir_.end()) {
      for (auto& nid : it->second) {
        rep.add_node_ids(nid);
        auto nit = nodes_.find(nid);
        rep.add_addresses(nit != nodes_.end() ? nit->second.address() : "");
      }
    }
    auto sit = obj_sizes_.find(req.object_id());
    if (sit != obj_sizes_.end()) rep.set_size(sit->second);
    Reply(fd, env, rep);
  }

  // ------------------------------------------------------------ actor table

  void HandleUpsertActor(int fd, const raytpu::Envelope& env) {
    raytpu::RegisterActorRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad RegisterActorRequest");
    const raytpu::ActorInfo& info = req.info();
    // Name collision check on first registration.
    if (env.method() == raytpu::REGISTER_ACTOR && !info.name().empty()) {
      auto it = named_.find({info.namespace_(), info.name()});
      if (it != named_.end() && it->second != info.actor_id()) {
        auto ait = actors_.find(it->second);
        if (ait != actors_.end() && ait->second.state() != "DEAD") {
          return ReplyError(fd, env, "actor name already taken: " + info.name());
        }
      }
    }
    ApplyUpsertActor(req);
    Journal(env.method(), env.body());
    std::string body;
    info.SerializeToString(&body);
    Publish("actors", info.state(), body);
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void ApplyUpsertActor(const raytpu::RegisterActorRequest& req) {
    const raytpu::ActorInfo& info = req.info();
    auto prev = actors_.find(info.actor_id());
    if (prev != actors_.end() && !prev->second.name().empty()) {
      named_.erase({prev->second.namespace_(), prev->second.name()});
    }
    actors_[info.actor_id()] = info;
    if (!info.name().empty() && info.state() != "DEAD") {
      named_[{info.namespace_(), info.name()}] = info.actor_id();
    }
  }

  void HandleGetActor(int fd, const raytpu::Envelope& env) {
    raytpu::GetActorRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad GetActorRequest");
    raytpu::ActorReply rep;
    auto it = actors_.find(req.actor_id());
    if (it != actors_.end()) {
      rep.set_found(true);
      *rep.mutable_info() = it->second;
    }
    Reply(fd, env, rep);
  }

  void HandleGetNamedActor(int fd, const raytpu::Envelope& env) {
    raytpu::GetNamedActorRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad GetNamedActorRequest");
    raytpu::ActorReply rep;
    auto it = named_.find({req.namespace_(), req.name()});
    if (it != named_.end()) {
      auto ait = actors_.find(it->second);
      if (ait != actors_.end()) {
        rep.set_found(true);
        *rep.mutable_info() = ait->second;
      }
    }
    Reply(fd, env, rep);
  }

  void HandleListActors(int fd, const raytpu::Envelope& env) {
    raytpu::ListActorsReply rep;
    for (auto& [id, info] : actors_) *rep.add_actors() = info;
    Reply(fd, env, rep);
  }

  // ------------------------------------------------------------ pg / job

  void HandleUpsertPg(int fd, const raytpu::Envelope& env) {
    raytpu::RegisterPgRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad RegisterPgRequest");
    pgs_[req.info().pg_id()] = req.info();
    Journal(env.method(), env.body());
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleRemovePg(int fd, const raytpu::Envelope& env) {
    raytpu::RemovePgRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad RemovePgRequest");
    pgs_.erase(req.pg_id());
    Journal(raytpu::REMOVE_PG, env.body());
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleListPgs(int fd, const raytpu::Envelope& env) {
    raytpu::ListPgsReply rep;
    for (auto& [id, info] : pgs_) *rep.add_pgs() = info;
    Reply(fd, env, rep);
  }

  void HandleRegisterJob(int fd, const raytpu::Envelope& env) {
    raytpu::RegisterJobRequest req;
    if (!req.ParseFromString(env.body()))
      return ReplyError(fd, env, "bad RegisterJobRequest");
    jobs_[req.info().job_id()] = req.info();
    Journal(raytpu::REGISTER_JOB, env.body());
    raytpu::Empty e;
    Reply(fd, env, e);
  }

  void HandleListJobs(int fd, const raytpu::Envelope& env) {
    raytpu::ListJobsReply rep;
    for (auto& [id, info] : jobs_) *rep.add_jobs() = info;
    Reply(fd, env, rep);
  }

  void HandleStats(int fd, const raytpu::Envelope& env) {
    raytpu::StatsReply rep;
    auto& m = *rep.mutable_counters();
    m["nodes_total"] = nodes_.size();
    uint64_t alive = 0;
    for (auto& [id, n] : nodes_)
      if (n.alive()) alive++;
    m["nodes_alive"] = alive;
    m["actors"] = actors_.size();
    m["pgs"] = pgs_.size();
    m["jobs"] = jobs_.size();
    m["objects_tracked"] = obj_dir_.size();
    m["connections"] = conns_.size();
    m["cluster_epoch"] = cluster_epoch_;
    for (auto& [k, v] : counters_) m[k] = v;
    Reply(fd, env, rep);
  }

  // ------------------------------------------------------------ persistence

  std::string SnapshotPath() { return data_dir_ + "/state_snapshot.pb"; }
  std::string JournalPath() { return data_dir_ + "/state_journal.pb"; }

  void OpenJournal() {
    journal_ = fopen(JournalPath().c_str(), "ab");
    if (journal_ == nullptr) perror("open journal");
  }

  void WriteSnapshot() {
    raytpu::StateSnapshot snap;
    for (auto& [id, info] : nodes_) *snap.add_nodes() = info;
    for (auto& [id, info] : actors_) *snap.add_actors() = info;
    for (auto& [id, info] : pgs_) *snap.add_pgs() = info;
    for (auto& [id, info] : jobs_) *snap.add_jobs() = info;
    for (auto& [ns, entries] : kv_) {
      for (auto& [k, v] : entries) {
        auto* e = snap.add_kv();
        e->set_ns(ns);
        e->set_key(k);
        e->set_value(v);
      }
    }
    snap.set_cluster_epoch(cluster_epoch_);
    std::string tmp = SnapshotPath() + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (f == nullptr) return;
    std::string data;
    snap.SerializeToString(&data);
    fwrite(data.data(), 1, data.size(), f);
    fclose(f);
    rename(tmp.c_str(), SnapshotPath().c_str());
    // Journal entries up to this snapshot are now redundant.
    if (journal_ != nullptr) {
      fclose(journal_);
      journal_ = nullptr;
    }
    FILE* j = fopen(JournalPath().c_str(), "wb");  // truncate
    if (j != nullptr) fclose(j);
    OpenJournal();
  }

  void LoadPersisted() {
    // 1. snapshot
    FILE* f = fopen(SnapshotPath().c_str(), "rb");
    if (f != nullptr) {
      std::string data;
      char buf[1 << 16];
      size_t n;
      while ((n = fread(buf, 1, sizeof(buf), f)) > 0) data.append(buf, n);
      fclose(f);
      raytpu::StateSnapshot snap;
      if (snap.ParseFromString(data)) {
        for (auto& info : snap.nodes()) nodes_[info.node_id()] = info;
        for (auto& info : snap.actors()) {
          actors_[info.actor_id()] = info;
          if (!info.name().empty() && info.state() != "DEAD")
            named_[{info.namespace_(), info.name()}] = info.actor_id();
        }
        for (auto& info : snap.pgs()) pgs_[info.pg_id()] = info;
        for (auto& info : snap.jobs()) jobs_[info.job_id()] = info;
        for (auto& e : snap.kv()) kv_[e.ns()][e.key()] = e.value();
        cluster_epoch_ = snap.cluster_epoch();
      }
    }
    // 2. journal replay
    f = fopen(JournalPath().c_str(), "rb");
    if (f != nullptr) {
      std::string data;
      char buf[1 << 16];
      size_t n;
      while ((n = fread(buf, 1, sizeof(buf), f)) > 0) data.append(buf, n);
      fclose(f);
      size_t off = 0;
      while (data.size() - off >= 4) {
        const unsigned char* p = (const unsigned char*)data.data() + off;
        uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                       (uint32_t(p[2]) << 8) | uint32_t(p[3]);
        if (data.size() - off - 4 < len) break;  // torn tail write
        raytpu::JournalRecord rec;
        if (rec.ParseFromString(data.substr(off + 4, len))) ReplayRecord(rec);
        off += 4 + len;
      }
    }
    // Give restored nodes a grace period to resume heartbeating.
    for (auto& [id, info] : nodes_) {
      if (info.alive()) hb_deadline_[id] = mono_ms() + 2 * hb_timeout_ms_;
    }
  }

  void ReplayRecord(const raytpu::JournalRecord& rec) {
    switch (rec.method()) {
      case raytpu::REGISTER_NODE: {
        raytpu::RegisterNodeRequest req;
        if (req.ParseFromString(rec.body())) ApplyRegisterNode(req);
        break;
      }
      case raytpu::MARK_NODE_DEAD: {
        raytpu::MarkNodeDeadRequest req;
        if (req.ParseFromString(rec.body())) ApplyMarkNodeDead(req);
        break;
      }
      case raytpu::DRAIN_NODE: {
        raytpu::DrainNodeRequest req;
        if (req.ParseFromString(rec.body())) ApplyDrainNode(req);
        break;
      }
      case raytpu::KV_PUT: {
        raytpu::KvPutRequest req;
        if (req.ParseFromString(rec.body())) kv_[req.ns()][req.key()] = req.value();
        break;
      }
      case raytpu::KV_DEL: {
        raytpu::KvDelRequest req;
        if (req.ParseFromString(rec.body())) {
          auto it = kv_.find(req.ns());
          if (it != kv_.end()) it->second.erase(req.key());
        }
        break;
      }
      case raytpu::REGISTER_ACTOR:
      case raytpu::UPDATE_ACTOR: {
        raytpu::RegisterActorRequest req;
        if (req.ParseFromString(rec.body())) ApplyUpsertActor(req);
        break;
      }
      case raytpu::REGISTER_PG:
      case raytpu::UPDATE_PG: {
        raytpu::RegisterPgRequest req;
        if (req.ParseFromString(rec.body())) pgs_[req.info().pg_id()] = req.info();
        break;
      }
      case raytpu::REMOVE_PG: {
        raytpu::RemovePgRequest req;
        if (req.ParseFromString(rec.body())) pgs_.erase(req.pg_id());
        break;
      }
      case raytpu::REGISTER_JOB: {
        raytpu::RegisterJobRequest req;
        if (req.ParseFromString(rec.body())) jobs_[req.info().job_id()] = req.info();
        break;
      }
      default:
        break;
    }
  }

  // -------------------------------------------------------------- members

  std::string auth_token_;
  std::string host_;
  int port_;
  std::string data_dir_;
  double hb_timeout_ms_;
  double snapshot_interval_s_;
  int listen_fd_ = -1;
  int epfd_ = -1;
  int timer_fd_ = -1;
  FILE* journal_ = nullptr;
  uint64_t cluster_epoch_ = 0;

  std::unordered_map<int, Conn> conns_;
  std::unordered_map<std::string, raytpu::NodeInfo> nodes_;
  std::unordered_map<std::string, double> hb_deadline_;  // mono ms
  std::unordered_map<std::string,
                     std::unordered_map<std::string, std::string>>
      kv_;
  std::unordered_map<std::string, raytpu::ActorInfo> actors_;
  std::map<std::pair<std::string, std::string>, std::string> named_;
  std::unordered_map<std::string, raytpu::PgInfo> pgs_;
  std::unordered_map<std::string, raytpu::JobInfo> jobs_;
  std::unordered_map<std::string, std::set<std::string>> obj_dir_;
  std::unordered_map<std::string, uint64_t> obj_sizes_;
  std::map<std::string, uint64_t> counters_;
};

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  std::string host = "127.0.0.1";
  std::string data_dir;
  std::string port_file;
  double hb_timeout_ms = 10000;
  double snapshot_interval_s = 30;
  std::string token_file;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&](const char* flag) -> std::string {
      if (i + 1 >= argc) {
        fprintf(stderr, "missing value for %s\n", flag);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--port") port = atoi(next("--port").c_str());
    else if (a == "--host") host = next("--host");
    else if (a == "--data-dir") data_dir = next("--data-dir");
    else if (a == "--port-file") port_file = next("--port-file");
    else if (a == "--heartbeat-timeout-ms")
      hb_timeout_ms = atof(next("--heartbeat-timeout-ms").c_str());
    else if (a == "--snapshot-interval-s")
      snapshot_interval_s = atof(next("--snapshot-interval-s").c_str());
    else if (a == "--token-file") token_file = next("--token-file");
    else {
      fprintf(stderr, "unknown flag %s\n", a.c_str());
      return 2;
    }
  }
  signal(SIGINT, on_signal);
  signal(SIGTERM, on_signal);
  signal(SIGPIPE, SIG_IGN);
  std::string auth_token;
  if (!token_file.empty()) {
    FILE* f = fopen(token_file.c_str(), "rb");
    if (!f) {
      fprintf(stderr, "cannot read --token-file %s\n", token_file.c_str());
      return 2;
    }
    char buf[4096];
    size_t n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
    auth_token.assign(buf, n);
    // Match Python's str.strip(): whitespace off both ends.
    while (!auth_token.empty() && isspace((unsigned char)auth_token.back()))
      auth_token.pop_back();
    size_t lead = 0;
    while (lead < auth_token.size() &&
           isspace((unsigned char)auth_token[lead]))
      lead++;
    auth_token.erase(0, lead);
  } else if (const char* t = getenv("RAY_TPU_AUTH_TOKEN")) {
    auth_token = t;
  }
  StateService svc(port, host, data_dir, hb_timeout_ms, snapshot_interval_s,
                   auth_token);
  return svc.Run(port_file);
}
