"""Build-on-first-use for the native components.

The wheel-less analogue of the reference's bazel build of the C++ core:
each ``.cc`` in this directory compiles to a shared library with the
system toolchain, cached beside the source and rebuilt when the source is
newer. No pybind11 — the libraries expose a C ABI consumed via ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE = {}  # raylint: guarded-by(_LOCK)


def _sanitize_flags() -> list:
    """RAY_TPU_SANITIZE=address|thread|undefined adds the corresponding
    -fsanitize instrumentation to every native build (the .bazelrc asan/
    tsan config role, reference ``.bazelrc:91-107``). Sanitized artifacts
    get a distinct suffix so they never shadow the production cache."""
    kind = os.environ.get("RAY_TPU_SANITIZE", "").strip()
    if not kind:
        return []
    if kind not in ("address", "thread", "undefined"):
        raise NativeBuildError(f"unknown RAY_TPU_SANITIZE={kind!r}")
    return [f"-fsanitize={kind}", "-g", "-fno-omit-frame-pointer"]


def _artifact_suffix() -> str:
    kind = os.environ.get("RAY_TPU_SANITIZE", "").strip()
    return f".{kind[0]}san" if kind else ""


class NativeBuildError(RuntimeError):
    pass


def load_native_library(name: str,
                        opt_flags: tuple = ()) -> Optional[ctypes.CDLL]:
    """Compile ``<name>.cc`` (if stale) and dlopen it. Returns None if no
    toolchain is available — callers fall back to pure-Python paths.

    ``opt_flags`` replaces the default ``-O2`` for sources that need the
    vectorizer (the quant kernels lose to numpy at -O2). If the toolchain
    rejects them (e.g. ``-march=native`` on an exotic target) the build
    retries at -O2 before giving up — a slower kernel beats no kernel."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cc")
        so = os.path.join(_DIR, f"lib{name}{_artifact_suffix()}.so")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                tmp = so + ".tmp"
                for flags in ([*opt_flags] if opt_flags else [], ["-O2"]):
                    cmd = ["g++", *(flags or ["-O2"]), "-std=c++17",
                           "-shared", "-fPIC", "-pthread",
                           *_sanitize_flags(), "-o", tmp, src]
                    try:
                        subprocess.run(cmd, check=True, capture_output=True,
                                       text=True)
                        break
                    except subprocess.CalledProcessError:
                        if not flags or flags == ["-O2"]:
                            raise
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            import logging
            logging.getLogger("ray_tpu").warning(
                "native %s unavailable, using pure-Python fallback: %s",
                name, detail.strip()[:500])
            lib = None
        _CACHE[name] = lib
        return lib


#: Flag set for the quant kernels: -O2 leaves the absmax scan scalar (it
#: loses to numpy); these turn both loops into packed integer-max /
#: convert and were measured ~3x faster than the fused numpy path.
QUANT_OPT_FLAGS = ("-O3", "-march=native", "-ffast-math", "-funroll-loops")


def _build_proto_binary(src_name: str, exe_prefix: str,
                        extra_flags: list) -> str:
    """Shared recipe for the protobuf-linked C++ binaries (state service,
    cpp worker demo): protoc gen + g++, mtime-cached, sanitizer-aware,
    tmp-file atomic replace (concurrent builders must not interleave)."""
    proto_dir = os.path.normpath(os.path.join(_DIR, os.pardir, "protocol"))
    proto = os.path.join(proto_dir, "raytpu.proto")
    src = os.path.join(_DIR, src_name)
    gen_dir = os.path.join(_DIR, "gen")
    pb_cc = os.path.join(gen_dir, "raytpu.pb.cc")
    exe = os.path.join(_DIR, f"{exe_prefix}{_artifact_suffix()}")
    with _LOCK:
        try:
            src_mtime = max(os.path.getmtime(src), os.path.getmtime(proto))
            if os.path.exists(exe) and os.path.getmtime(exe) >= src_mtime:
                return exe
            os.makedirs(gen_dir, exist_ok=True)
            if (not os.path.exists(pb_cc)
                    or os.path.getmtime(pb_cc) < os.path.getmtime(proto)):
                subprocess.run(
                    ["protoc", f"--proto_path={proto_dir}",
                     f"--cpp_out={gen_dir}", proto],
                    check=True, capture_output=True, text=True)
            import tempfile
            fd, tmp = tempfile.mkstemp(prefix=f"{exe_prefix}_", dir=_DIR)
            os.close(fd)
            subprocess.run(
                ["g++", "-O2", "-std=c++17", *extra_flags,
                 *_sanitize_flags(), "-o", tmp, src, pb_cc,
                 f"-I{gen_dir}", f"-I{_DIR}", "-lprotobuf", "-lpthread"],
                check=True, capture_output=True, text=True)
            os.chmod(tmp, 0o755)
            os.replace(tmp, exe)
        except subprocess.CalledProcessError as e:
            raise NativeBuildError(
                f"{exe_prefix} build failed:\n{e.stderr}") from e
        except OSError as e:
            raise NativeBuildError(f"{exe_prefix} build failed: {e}") from e
        return exe


def build_cpp_worker_demo() -> str:
    """Build the C++ worker-API demo driver (``cpp_worker.cc``): the
    cross-language client that joins a cluster, round-trips the KV and
    invokes Python named functions with JSON args."""
    return _build_proto_binary("cpp_worker.cc", "raytpu_cpp_demo",
                               ["-DRAYTPU_CPP_DEMO_MAIN"])


def build_state_service() -> str:
    """Build the C++ state-service binary (protoc gen + g++ + libprotobuf);
    returns the executable path. Cached until sources change."""
    return _build_proto_binary("state_service.cc", "raytpu_state_service",
                               [])
