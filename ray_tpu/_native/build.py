"""Build-on-first-use for the native components.

The wheel-less analogue of the reference's bazel build of the C++ core:
each ``.cc`` in this directory compiles to a shared library with the
system toolchain, cached beside the source and rebuilt when the source is
newer. No pybind11 — the libraries expose a C ABI consumed via ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE = {}


class NativeBuildError(RuntimeError):
    pass


def load_native_library(name: str) -> Optional[ctypes.CDLL]:
    """Compile ``<name>.cc`` (if stale) and dlopen it. Returns None if no
    toolchain is available — callers fall back to pure-Python paths."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cc")
        so = os.path.join(_DIR, f"lib{name}.so")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                tmp = so + ".tmp"
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, src],
                    check=True, capture_output=True, text=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            import logging
            logging.getLogger("ray_tpu").warning(
                "native %s unavailable, using pure-Python fallback: %s",
                name, detail.strip()[:500])
            lib = None
        _CACHE[name] = lib
        return lib
