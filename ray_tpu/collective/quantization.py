"""Block-wise quantization for the collective compression tier.

EQuARX-style dynamic block quantization (arxiv 2506.17615): a tensor is
cut into contiguous blocks of ``quant_block_bytes`` input bytes; each
block ships a one-byte-per-element payload plus one f32 absmax-derived
scale. Two schemes:

- ``q8``  — symmetric int8, scale = absmax/127, round-to-nearest.
  Per-element error is bounded by scale/2 = absmax/254 of the block.
- ``fp8`` — ``ml_dtypes.float8_e4m3fn``, scale = absmax/448 (the e4m3
  finite max), so the block's dynamic range maps onto the fp8 exponent
  range. Cheaper relative error near zero, coarser near absmax.

Dequantization is fused into the reduction (`accumulate`): payloads are
widened to f32 and summed at full precision — quantized ranks never
accumulate in int8, so the only error is the one round-trip per rank.

The q8 path has a native kernel (``_native/quant.cc``, built on first
use with vectorization flags) ~3x faster than the fused numpy fallback;
payloads agree to the last bit of rounding (scales within one f32 ULP,
both round-to-nearest-even), so ranks may mix the two.

Wire accounting: ``Quantized.wire_bytes`` = payload + scales bytes —
what actually crosses a link — distinct from the logical tensor bytes
the comms ledger also records. At the default 256-byte block an f32
tensor ships at ~0.27x (64 payload bytes + 4 scale bytes per 256
logical bytes).

Non-finite blocks quantize to a poisoned ``scale = -1`` (payload
zeroed); dequantization rejects them loudly instead of shipping silent
garbage — matching the native kernel bit-for-bit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

SCHEMES = ("none", "q8", "fp8")

_FP8_MAX = 448.0  # float8_e4m3fn finite max

_native_lib = None
_native_tried = False


def _native():
    """The quant kernel library, built on first use (None = numpy only)."""
    global _native_lib, _native_tried
    if not _native_tried:
        from ray_tpu._native.build import QUANT_OPT_FLAGS, load_native_library
        _native_lib = load_native_library("quant", opt_flags=QUANT_OPT_FLAGS)
        _native_tried = True
    return _native_lib


def _fp8_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.float8_e4m3fn)


@dataclass(frozen=True)
class Quantized:
    """One rank's compressed collective payload."""

    scheme: str            # "q8" | "fp8"
    payload: np.ndarray    # int8 (q8) or float8_e4m3fn (fp8), flat
    scales: np.ndarray     # f32, one per block (-1 poisons a non-finite block)
    shape: tuple           # original tensor shape
    dtype: Any             # original tensor dtype (np.dtype)
    block: int             # elements per block

    @property
    def nbytes(self) -> int:
        """Logical bytes of the tensor this payload represents (the comms
        ledger's ``bytes`` column; ``wire_bytes`` is what moved)."""
        return int(np.prod(self.shape, dtype=np.int64)) * \
            np.dtype(self.dtype).itemsize

    @property
    def wire_bytes(self) -> int:
        return int(self.payload.nbytes + self.scales.nbytes)


@dataclass(frozen=True)
class QuantFault:
    """Deposited at the rendezvous in place of a payload when a rank's
    quantization step raised (e.g. a chaos ``collective.quant`` fault).
    The compute raises the carried error into the shared outcome, so
    every rank fails loudly instead of the peers timing out waiting for
    the faulted rank's payload."""

    error: BaseException
    shape: tuple
    dtype: Any


def block_elems(block_bytes: int, dtype) -> int:
    """Elements per block: ``quant_block_bytes`` counts *input* bytes, so
    the scale overhead per block is fixed regardless of input width."""
    return max(1, int(block_bytes) // max(1, np.dtype(dtype).itemsize))


def quantizable(arr) -> bool:
    """Only real float tensors compress; ints/bools/complex pass through
    at full precision (their collectives are typically tiny control
    values where bit-exactness matters more than bytes)."""
    return np.dtype(arr.dtype).kind == "f"


def active(config, arr) -> bool:
    return (config is not None
            and getattr(config, "compression", "none") != "none"
            and quantizable(arr))


# -- q8 -----------------------------------------------------------------------


def _q8_quantize_native(flat: np.ndarray, be: int, lib):
    import ctypes
    n = flat.size
    nb = -(-n // be)
    q = np.empty(n, np.int8)
    scales = np.empty(nb, np.float32)
    lib.rtq_q8_quantize(
        ctypes.c_void_p(flat.ctypes.data), ctypes.c_int64(n),
        ctypes.c_int64(be), ctypes.c_void_p(q.ctypes.data),
        ctypes.c_void_p(scales.ctypes.data))
    return q, scales


def _blocked(flat: np.ndarray, be: int) -> np.ndarray:
    """(nb, be) view of ``flat`` zero-padded to a whole number of blocks."""
    n = flat.size
    nb = -(-n // be)
    if nb * be == n:
        return flat.reshape(nb, be)
    padded = np.zeros(nb * be, flat.dtype)
    padded[:n] = flat
    return padded.reshape(nb, be)

def _np_quantize(flat: np.ndarray, be: int, scheme: str):
    blocks = _blocked(flat, be)
    absmax = np.max(np.abs(blocks), axis=1)
    bad = ~np.isfinite(absmax)
    if scheme == "q8":
        scales = (absmax / 127.0).astype(np.float32)
        safe = np.where(scales > 0.0, scales, 1.0)
        q = np.clip(np.rint(blocks / safe[:, None]), -127, 127) \
            .astype(np.int8)
    else:
        scales = (absmax / _FP8_MAX).astype(np.float32)
        safe = np.where(scales > 0.0, scales, 1.0)
        # clip: e4m3fn has no inf, so values a hair over the finite max
        # (scale rounding) must saturate, not wrap to nan
        q = np.clip(blocks / safe[:, None], -_FP8_MAX,
                    _FP8_MAX).astype(_fp8_dtype())
    if bad.any():
        q[bad] = 0
        scales[bad] = -1.0
    return q.reshape(-1)[:flat.size], scales


def quantize(tensor, config, *, group: str = "default", op: str = "",
             rank: int = -1) -> Quantized:
    """Compress one rank's tensor per its group config.

    This is the ``collective.quant`` chaos seam: a fault schedule can
    error/delay exactly one rank's quantization step (labels: group, op,
    rank) — the deterministic drill for "a quantized op fails loudly and
    retries clean". Quantize time lands in the ``collective.quantize``
    perf histogram so compression cost is visible next to op latency.
    """
    from ray_tpu import chaos
    from ray_tpu.observability import perf
    if chaos.ENABLED:
        chaos.inject("collective.quant", group=group, op=op, rank=str(rank))
    t0 = time.monotonic() if perf.ENABLED else 0.0
    scheme = config.compression
    if scheme not in ("q8", "fp8"):
        raise ValueError(f"unknown compression scheme {scheme!r}; "
                         f"use one of {SCHEMES}")
    arr = np.asarray(tensor)
    be = block_elems(config.quant_block_bytes, arr.dtype)
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    lib = _native() if scheme == "q8" else None
    if lib is not None:
        q, scales = _q8_quantize_native(flat, be, lib)
    else:
        q, scales = _np_quantize(flat, be, scheme)
    out = Quantized(scheme=scheme, payload=q, scales=scales,
                    shape=tuple(arr.shape), dtype=np.dtype(arr.dtype),
                    block=be)
    if perf.ENABLED:
        perf.observe("collective.quantize", (time.monotonic() - t0) * 1e3)
    return out


def _check_scales(q: Quantized) -> None:
    if q.scales.size and float(q.scales.min()) < 0.0:
        raise ValueError(
            f"{q.scheme} payload carries poisoned block scale(s): the "
            f"source tensor had non-finite values; refusing to dequantize")


def _dequant_f32(q: Quantized) -> np.ndarray:
    """Flat f32 dequantization (the widen half of the fused reduce)."""
    _check_scales(q)
    n = int(np.prod(q.shape, dtype=np.int64))
    lib = _native() if q.scheme == "q8" else None
    if lib is not None:
        import ctypes
        out = np.empty(n, np.float32)
        lib.rtq_q8_dequant(
            ctypes.c_void_p(q.payload.ctypes.data), ctypes.c_void_p(
                q.scales.ctypes.data), ctypes.c_int64(n),
            ctypes.c_int64(q.block), ctypes.c_void_p(out.ctypes.data))
        return out
    blocks = _blocked(q.payload.astype(np.float32), q.block)
    return (blocks * q.scales[:, None]).reshape(-1)[:n]


def dequantize(q: Quantized) -> np.ndarray:
    """Round-trip back to the original shape and dtype."""
    return _dequant_f32(q).reshape(q.shape).astype(q.dtype, copy=False)


def accumulate(q: Quantized, acc: np.ndarray) -> None:
    """``acc += dequant(q)`` fused at f32 — the reduction never sums in
    int8. ``acc`` is a flat f32 array of the tensor's element count."""
    _check_scales(q)
    lib = _native() if q.scheme == "q8" else None
    if lib is not None:
        import ctypes
        lib.rtq_q8_dequant_add(
            ctypes.c_void_p(q.payload.ctypes.data),
            ctypes.c_void_p(q.scales.ctypes.data),
            ctypes.c_int64(acc.size), ctypes.c_int64(q.block),
            ctypes.c_void_p(acc.ctypes.data))
        return
    acc += _dequant_f32(q)


def reduce_quantized(items, reduce_np=None) -> np.ndarray:
    """Reduce a list of same-shape :class:`Quantized` payloads at full
    precision. SUM takes the fused accumulate path; other reductions
    (``reduce_np`` from the backend's numpy table) widen each payload
    first. Returns the reduced tensor in the original shape/dtype."""
    first = items[0]
    if reduce_np is None:  # SUM
        acc = _dequant_f32(first).copy()
        for q in items[1:]:
            accumulate(q, acc)
        return acc.reshape(first.shape).astype(first.dtype, copy=False)
    widened = np.stack([_dequant_f32(q).reshape(q.shape) for q in items])
    return reduce_np(widened).astype(first.dtype, copy=False)


def hierarchical_allreduce(xs, config, reduce_np=None, *,
                           group: str = "default", op_name: str = "allreduce"):
    """Two-level allreduce over rank-ordered tensors ``xs``.

    Contiguous spans of ``ranks_per_host`` ranks form a "host". The
    intra-host reduction runs at full precision (that hop is the
    in-process/ICI path, where bytes are cheap), then ONLY the per-host
    partials cross the inter-host seam quantized — the reduce-scatter/
    allreduce/allgather decomposition collapsed to its byte-accounting
    essence for in-process groups, where both hops are function calls
    but the wire ledger must still tell them apart.

    Returns ``(reduced, wire_per_rank)``: ``wire_per_rank`` is each
    rank's share of the quantized inter-host traffic (total quantized
    partial bytes / world), which is what makes hierarchical groups
    report *less* wire than flat quantized ones — the point of the
    decomposition.
    """
    world = len(xs)
    rph = config.ranks_per_host
    if rph <= 1 or world % rph or world == rph:
        raise ValueError(
            f"hierarchical allreduce needs 1 < ranks_per_host < world and "
            f"ranks_per_host | world; got ranks_per_host={rph} world={world}")
    hosts = world // rph
    partials = []
    for h in range(hosts):
        span = np.stack([np.asarray(xs[r])
                         for r in range(h * rph, (h + 1) * rph)])
        partials.append(np.sum(span, axis=0) if reduce_np is None
                        else reduce_np(span))
    qs = [quantize(p, config, group=group, op=op_name, rank=h * rph)
          for h, p in enumerate(partials)]
    red = reduce_quantized(qs, reduce_np)
    wire = sum(q.wire_bytes for q in qs) // world
    return red, wire


def qmeta(config, arr) -> tuple:
    """The (scheme, block_elems) pair folded into collective fingerprints:
    mixed-scheme ranks must raise CollectiveDivergenceError, not corrupt
    the reduction with a half-quantized accumulate."""
    if not active(config, arr):
        return ("none", 0)
    return (config.compression,
            block_elems(config.quant_block_bytes, arr.dtype))
