"""Collective types and options.

Parity with ``python/ray/util/collective/types.py``: ``Backend`` and
``ReduceOp`` enums plus per-op options dataclasses.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import List


class Backend:
    """Collective backend names. The reference supports NCCL/GLOO and rejects
    MPI (``collective.py:59-60``); here the tensor plane is XLA — collectives
    compile onto ICI — with a CPU (numpy) backend for host tensors and tests.
    NCCL/GLOO names are accepted as aliases so reference code ports run."""

    XLA = "xla"
    CPU = "cpu"

    _ALIASES = {"nccl": XLA, "gloo": CPU, "xla": XLA, "cpu": CPU}

    def __new__(cls, name: str = "xla"):
        backend = cls._ALIASES.get(str(name).lower())
        if backend is None:
            if str(name).lower() == "mpi":
                raise ValueError("MPI backend is not supported")
            raise ValueError(f"unknown collective backend {name!r}; "
                             f"use 'xla' or 'cpu'")
        return backend


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3


unset_timeout_ms = 30000


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = unset_timeout_ms


@dataclass
class BarrierOptions:
    timeout_ms: int = unset_timeout_ms


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class AllGatherOptions:
    timeout_ms: int = unset_timeout_ms


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = unset_timeout_ms


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = unset_timeout_ms
